"""Durable publisher outbox: a broker outage buffers, it doesn't raise.

Every publisher used to call the transport inline: `RemoteBus.publish`
was one Publish RPC, and a dead broker raised straight into the serving
path (a worker's commit, the orchestrator's dispatch tick).  The
reference never had this problem — its sidecar was local and always up,
and the *sidecar* owned delivery to the real broker.  This module is
that sidecar half: publishes land in a bounded in-process queue (with an
optional spill-to-disk WAL so a publisher restart re-sends what it had
buffered), and a background flusher drives them to the transport through
the shared resiliency layer (`utils/resilience.py`): per-frame
`retry_call` with jittered exponential backoff plus a circuit breaker on
target ``bus`` — an outage degrades to buffered-and-retried, visible as
``bus_outbox_depth`` / ``resilience_circuit_state{target="bus"}``.

Ordering is preserved (head-of-line: the flusher never skips a frame),
and the bound is a hard one: a full outbox raises :class:`OutboxFull`
into the publisher, which is the backpressure signal the orchestrator's
dispatch valve watches via :meth:`DurableOutbox.near_full`
(`orchestrator/orchestrator.py:_backpressure_engaged`).

`OutboxBus` is the drop-in wrapper: ``publish`` goes through the outbox,
everything else (subscribe, drain, pending_count, ...) delegates to the
inner bus, and the inner bus's lifetime stays the caller's problem.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from ..utils import resilience, trace
from ..utils.metrics import REGISTRY, MetricsRegistry
from .payload import serialize_payload
from .spool import _fold_lines

logger = logging.getLogger("dct.bus.outbox")

OUTBOX_TARGET = "bus"  # the circuit-breaker target name
WAL_FILE = "outbox.jsonl"

DEFAULT_MAX_FRAMES = 1024
DEFAULT_NEAR_FULL_FRACTION = 0.8


class OutboxFull(RuntimeError):
    """The bounded outbox is at capacity — the publish was NOT accepted."""

    def __init__(self, depth: int, max_frames: int):
        super().__init__(
            f"bus outbox full ({depth}/{max_frames} frames buffered)")
        self.depth = depth
        self.max_frames = max_frames


@dataclass(frozen=True)
class OutboxConfig:
    """Knobs for one publisher's outbox (``bus.outbox_max_frames`` and
    friends in config.example.yaml)."""

    dir: str = ""                    # spill-to-disk WAL; "" = memory-only
    max_frames: int = DEFAULT_MAX_FRAMES
    flush_wait_s: float = 0.05       # idle/backoff granularity
    retry_attempts: int = 4          # per retry_call round (outer loop is
                                     # unbounded — frames are never dropped)
    retry_base_s: float = 0.05
    retry_max_s: float = 1.0
    breaker_threshold: int = 5
    breaker_recovery_s: float = 1.0
    fsync: bool = True
    fsync_every: int = 16            # batched, the TopicSpool discipline:
                                     # flush per line (process-crash safe),
                                     # fsync every N (OS-crash window)
    compact_every: int = 256
    near_full_fraction: float = DEFAULT_NEAR_FULL_FRACTION


class DurableOutbox:
    """Bounded spill-to-disk publish queue + resilience-wrapped flusher.

    ``send(topic, payload)`` is the transport call (e.g. the Publish RPC);
    it is invoked from the flusher thread only, through
    ``resilience.retry_call`` + the ``bus`` circuit breaker.
    """

    def __init__(self, send: Callable[[str, Any], None],
                 cfg: OutboxConfig = OutboxConfig(),
                 name: str = OUTBOX_TARGET,
                 registry: MetricsRegistry = REGISTRY,
                 breaker_target: Optional[str] = None):
        self._send = send
        self.cfg = cfg
        self.name = name
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        # (seq, topic, payload-or-None, serialized bytes); payload is the
        # live object when the publish happened in THIS process (no
        # decode cost on flush), None for WAL-reloaded entries.
        self._q: "deque[Tuple[int, str, Any, bytes]]" = deque()
        self._seq = 0
        self._wal_fh = None
        self._wal_puts = 0
        self._wal_dones = 0
        self._since_fsync = 0
        self._retry = resilience.RetryPolicy(
            max_attempts=max(1, cfg.retry_attempts),
            base_delay_s=cfg.retry_base_s, max_delay_s=cfg.retry_max_s,
            jitter=0.2)
        # ONE breaker target ("bus") whatever the publisher: every outbox
        # in a process is talking to the same broker, so they share the
        # resilience_circuit_state{target="bus"} series; the depth/flow
        # series are labeled per publisher so co-hosted outboxes (e.g.
        # the gate's local + worker ones) don't clobber each other.
        # The partitioned bus (`bus/partition.py`) is the exception:
        # its outboxes each talk to a DIFFERENT broker shard, so it
        # passes a per-shard ``breaker_target`` — one shard's outage
        # must not open the circuit for its healthy siblings.
        self._breaker = resilience.CircuitBreaker(
            breaker_target or OUTBOX_TARGET,
            failure_threshold=cfg.breaker_threshold,
            recovery_timeout_s=cfg.breaker_recovery_s, registry=registry)
        self.m_depth = registry.gauge(
            "bus_outbox_depth",
            "publishes buffered awaiting the broker (bus/outbox.py)"
        ).labels(publisher=name)
        self.m_capacity = registry.gauge(
            "bus_outbox_capacity", "outbox frame bound (max_frames)"
        ).labels(publisher=name)
        self.m_flushed = registry.counter(
            "bus_outbox_flushed_total",
            "buffered publishes delivered to the transport"
        ).labels(publisher=name)
        self.m_rejected = registry.counter(
            "bus_outbox_rejected_total",
            "publishes refused because the outbox was full"
        ).labels(publisher=name)
        self.m_capacity.set(float(cfg.max_frames))
        self.m_depth.set(0.0)
        if cfg.dir:
            os.makedirs(cfg.dir, exist_ok=True)
            self._reload()
        self._thread = threading.Thread(target=self._flush_loop,
                                        daemon=True,
                                        name=f"dct-outbox-{name}")
        self._thread.start()

    # -- introspection ------------------------------------------------------
    @property
    def wal_path(self) -> str:
        return os.path.join(self.cfg.dir, WAL_FILE) if self.cfg.dir else ""

    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def near_full(self) -> bool:
        """True once the buffer crosses the near-full fraction of its
        bound — the orchestrator's dispatch valve ENGAGES on this."""
        with self._lock:
            return len(self._q) >= max(
                1, int(self.cfg.max_frames * self.cfg.near_full_fraction))

    def below_low_water(self) -> bool:
        """True once the buffer has drained to half the near-full mark —
        the valve RELEASES on this (distinct marks = hysteresis, so a
        depth hovering at the boundary can't flap the valve per tick)."""
        with self._lock:
            high = max(1, int(self.cfg.max_frames
                              * self.cfg.near_full_fraction))
            return len(self._q) <= high // 2

    @property
    def circuit_state(self) -> str:
        return self._breaker.state

    # -- WAL ----------------------------------------------------------------
    def _reload(self) -> None:
        """Fold put/done events into the pending set (publisher restart:
        what was buffered but never delivered is re-sent).  Torn-tail /
        corrupt-line handling is the spool's (`spool._fold_lines`) — ONE
        crash-recovery parsing rule for every WAL in this package."""
        pending: "dict[int, Tuple[str, bytes]]" = {}
        path = self.wal_path
        for ev in _fold_lines(path):
            seq = int(ev.get("s", -1))
            if seq < 0:
                continue
            if ev.get("k") == "put":
                try:
                    data = base64.b64decode(ev.get("d", ""))
                except (ValueError, TypeError):
                    continue
                pending[seq] = (str(ev.get("t", "")), data)
            elif ev.get("k") == "done":
                pending.pop(seq, None)
        for seq in sorted(pending):
            topic, data = pending[seq]
            self._q.append((seq, topic, None, data))
            # Construction-time (the flusher thread doesn't exist yet).
            self._seq = max(self._seq, seq + 1)  # crawlint: disable=LCK001
        if pending:
            logger.info("outbox reloaded %d buffered publish(es) from %s",
                        len(pending), path)
        self.m_depth.set(float(len(self._q)))

    def _wal_append_locked(self, ev: dict) -> None:
        if not self.cfg.dir:
            return
        if self._wal_fh is None:
            # Caller holds _lock (the `_locked` suffix contract).
            self._wal_fh = open(self.wal_path, "a",  # crawlint: disable=LCK001,LCK002
                                encoding="utf-8")
        self._wal_fh.write(json.dumps(ev) + "\n")
        self._wal_fh.flush()
        self._since_fsync += 1  # crawlint: disable=LCK001
        if self.cfg.fsync and self._since_fsync >= max(
                1, self.cfg.fsync_every):
            # fsync per frame would serialize the publish hot path on
            # disk latency; batching bounds the OS-crash window instead
            # (a process crash loses nothing — lines are flushed).
            os.fsync(self._wal_fh.fileno())
            self._since_fsync = 0  # crawlint: disable=LCK001

    def _wal_maybe_compact_locked(self) -> None:
        # Once the done-prefix dominates, atomically rewrite the WAL as
        # just the pending puts (the TopicSpool discipline).  Waiting for
        # an EMPTY queue would never fire under sustained load with a
        # standing depth, growing the file for the life of the process.
        if not self.cfg.dir:
            return
        total = self._wal_puts + self._wal_dones
        if total < self.cfg.compact_every or self._wal_dones * 2 < total:
            return
        tmp = self.wal_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:  # crawlint: disable=LCK002
            for seq, topic, _payload, data in self._q:
                f.write(json.dumps({
                    "k": "put", "s": seq, "t": topic,
                    "d": base64.b64encode(data).decode("ascii")}) + "\n")
            f.flush()
            if self.cfg.fsync:
                os.fsync(f.fileno())
        if self._wal_fh is not None:
            try:
                self._wal_fh.close()
            except OSError:
                pass
            self._wal_fh = None  # crawlint: disable=LCK001
        os.replace(tmp, self.wal_path)
        # Caller holds _lock (the `_locked` suffix contract).
        self._wal_puts = len(self._q)  # crawlint: disable=LCK001
        self._wal_dones = 0  # crawlint: disable=LCK001

    # -- publish side -------------------------------------------------------
    def publish(self, topic: str, payload: Any) -> None:
        """Accept a publish into the buffer (raises :class:`OutboxFull`
        at the bound).  The trace parent is stamped HERE — the flusher
        thread has no span context, so injection at enqueue is what keeps
        the publish site in the trace."""
        payload = trace.inject(payload)
        # Serialize only when a spill WAL needs the bytes: a memory-only
        # outbox flushes the live object, so serializing here would be
        # pure hot-path waste.
        data = serialize_payload(payload) if self.cfg.dir else b""
        with self._lock:
            if len(self._q) >= self.cfg.max_frames:
                self.m_rejected.inc()
                raise OutboxFull(len(self._q), self.cfg.max_frames)
            seq = self._seq
            self._seq += 1
            self._wal_append_locked({
                "k": "put", "s": seq, "t": topic,
                "d": base64.b64encode(data).decode("ascii")})
            self._wal_puts += 1
            self._q.append((seq, topic, payload, data))
            self.m_depth.set(float(len(self._q)))
        self._wake.set()

    # -- flusher ------------------------------------------------------------
    def _deliver(self, topic: str, payload: Any, data: bytes) -> None:
        if payload is None:
            # WAL-reloaded frame: recover the object form when it is
            # JSON (the transports re-serialize), else send raw bytes.
            try:
                payload = json.loads(data.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                payload = data
        self._send(topic, payload)

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                head = self._q[0] if self._q else None
            if head is None:
                if self._stop.is_set():
                    return
                self._wake.wait(self.cfg.flush_wait_s)
                self._wake.clear()
                continue
            seq, topic, payload, data = head
            try:
                resilience.retry_call(
                    self._deliver, topic, payload, data,
                    retry=self._retry, op=f"bus.outbox.{self.name}",
                    stop=self._stop, breaker=self._breaker)
            except Exception as e:
                # Exhausted this round (or the circuit is open): the
                # frame STAYS at the head — never dropped — and the loop
                # backs off before the next round.
                if self._stop.is_set():
                    # Closing against a dead broker: keep the WAL — the
                    # next process re-sends — but stop burning retries.
                    return
                logger.warning(
                    "outbox flush of %s deferred (%d buffered): %s",
                    topic, self.depth(), e)
                self._stop.wait(self.cfg.flush_wait_s)
                continue
            with self._lock:
                if self._q and self._q[0][0] == seq:
                    self._q.popleft()
                self._wal_append_locked({"k": "done", "s": seq})
                self._wal_dones += 1
                self._wal_maybe_compact_locked()
                self.m_depth.set(float(len(self._q)))
            self.m_flushed.inc()

    # -- lifecycle ----------------------------------------------------------
    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until every buffered publish has been delivered (or the
        timeout passes); returns True when empty."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.depth() == 0:
                return True
            time.sleep(0.01)
        return self.depth() == 0

    def close(self, drain_s: float = 5.0) -> None:
        """Try to drain, then stop the flusher.  Undelivered frames stay
        in the WAL (when one is configured) for the next process.
        Idempotent: a second close (e.g. RemoteBus.close after a chaos
        kill already stopped the outbox) returns immediately instead of
        burning another drain window."""
        if self._stop.is_set():
            return
        if drain_s > 0:
            self.drain(timeout_s=drain_s)
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=max(2.0, drain_s))
        with self._lock:
            remaining = len(self._q)
            if self._wal_fh is not None:
                try:
                    if self._since_fsync:
                        os.fsync(self._wal_fh.fileno())
                    self._wal_fh.close()
                except OSError:
                    pass
                self._wal_fh = None  # crawlint: disable=LCK001
        if remaining:
            log = logger.warning if self.cfg.dir else logger.error
            log("outbox closed with %d undelivered publish(es)%s",
                remaining,
                " (kept in the WAL for the next run)" if self.cfg.dir
                else " LOST (no spill dir configured)")


class OutboxBus:
    """Any bus, with ``publish`` routed through a :class:`DurableOutbox`.

    The wrapper owns the outbox; the inner bus's lifetime belongs to the
    caller (``close()`` drains and stops the outbox, then closes the
    inner bus — pass ``close_inner=False`` to keep it open)."""

    def __init__(self, inner, cfg: OutboxConfig = OutboxConfig(),
                 name: str = OUTBOX_TARGET,
                 registry: MetricsRegistry = REGISTRY,
                 close_inner: bool = True):
        self.inner = inner
        self._close_inner = close_inner
        self.outbox = DurableOutbox(inner.publish, cfg, name=name,
                                    registry=registry)

    def publish(self, topic: str, payload: Any) -> None:
        self.outbox.publish(topic, payload)

    def close(self) -> None:
        self.outbox.close()
        if self._close_inner:
            close = getattr(self.inner, "close", None)
            if callable(close):
                close()

    def __getattr__(self, name):
        return getattr(self.inner, name)
