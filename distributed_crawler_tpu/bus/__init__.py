"""Message bus: typed envelopes, record-batch codec, in-memory + gRPC transports.

The reference's communication fabric was the Dapr sidecar (pubsub over Redis
Streams, SURVEY.md §2.4); this build brings the bus in-tree:

- `messages`: typed envelopes with validation, topics, priorities, trace IDs
  (`distributed/messages.go:11-333`)
- `codec`: the record-batching codec the north star adds — fixed-size batches
  of Post records, length-prefixed zstd/zlib frames, for streaming crawl
  output to the TPU inference worker over gRPC/DCN
- `inmemory`: broker-free bus with the reference's at-least-once semantics
  (decode error -> drop, handler error -> retry; `distributed/pubsub.go:149-254`)
- `grpc_bus`: DCN transport — a generic gRPC publish/subscribe service
- `spool`: the broker's durable memory — per-topic WAL + persisted
  dead-letter queue (`GrpcBusServer(spool_dir=...)` survives its own death)
- `outbox`: bounded durable publisher outbox — a broker outage buffers
  and retries instead of raising into the serving path
- `partition`: the 1→N control plane — a stable consistent-hash
  `ShardMap` plus `PartitionedBus`, which puts N broker shards (each a
  stock `GrpcBusServer` with its OWN spool dir) behind this same bus
  interface: pull topics route by post_uid/work-item key, fan-out
  topics broadcast with subscriber-side dedupe, and a dead shard's
  frames park in that shard's outbox WAL until it returns

On-slice tensor communication is NOT this bus's job: that rides XLA
collectives over ICI (see `parallel/`).
"""

from .codec import (
    MESSAGE_REGISTRY,
    RecordBatch,
    decode_frames,
    decode_message,
    encode_frame,
)
from .inmemory import InMemoryBus
from .outbox import DurableOutbox, OutboxBus, OutboxConfig, OutboxFull
from .partition import (
    BROADCAST_TOPICS,
    PartitionedBus,
    ShardMap,
    channel_of,
    default_shard_ids,
    routing_key,
    shard_spool_dirs,
    validate_shard_spool_dirs,
)
from .spool import BusSpool, DeadLetter, DeadLetterSpool, TopicSpool
from .messages import (
    PRIORITY_HIGH,
    PRIORITY_LOW,
    PRIORITY_MEDIUM,
    TOPIC_CHAOS,
    TOPIC_INFERENCE_BATCHES,
    TOPIC_INFERENCE_RESULTS,
    TOPIC_JOBS,
    TOPIC_ORCHESTRATOR,
    TOPIC_RESULTS,
    TOPIC_WORK_QUEUE,
    TOPIC_WORKER_STATUS,
    ChaosMessage,
    ControlMessage,
    DiscoveredPage,
    ResultMessage,
    StatusMessage,
    WorkItem,
    WorkItemConfig,
    WorkQueueMessage,
    WorkResult,
    new_trace_id,
    pubsub_topics,
)

__all__ = [
    "WorkItem",
    "WorkItemConfig",
    "WorkQueueMessage",
    "WorkResult",
    "ResultMessage",
    "DiscoveredPage",
    "StatusMessage",
    "ControlMessage",
    "ChaosMessage",
    "new_trace_id",
    "pubsub_topics",
    "RecordBatch",
    "encode_frame",
    "decode_frames",
    "decode_message",
    "MESSAGE_REGISTRY",
    "InMemoryBus",
    "PRIORITY_HIGH",
    "PRIORITY_MEDIUM",
    "PRIORITY_LOW",
    "TOPIC_WORK_QUEUE",
    "TOPIC_RESULTS",
    "TOPIC_WORKER_STATUS",
    "TOPIC_ORCHESTRATOR",
    "TOPIC_INFERENCE_BATCHES",
    "TOPIC_INFERENCE_RESULTS",
    "TOPIC_JOBS",
    "TOPIC_CHAOS",
    "GrpcBusServer",
    "GrpcBusClient",
    "RemoteBus",
    "BusSpool",
    "TopicSpool",
    "DeadLetterSpool",
    "DeadLetter",
    "DurableOutbox",
    "OutboxBus",
    "OutboxConfig",
    "OutboxFull",
    "ShardMap",
    "PartitionedBus",
    "BROADCAST_TOPICS",
    "routing_key",
    "channel_of",
    "default_shard_ids",
    "shard_spool_dirs",
    "validate_shard_spool_dirs",
]


def __getattr__(name):
    # The gRPC transport re-exports resolve lazily so the bus package (and
    # the InMemoryBus everything hermetic uses) stays importable without
    # grpcio installed.
    if name in ("GrpcBusServer", "GrpcBusClient", "RemoteBus"):
        from . import grpc_bus

        return getattr(grpc_bus, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
