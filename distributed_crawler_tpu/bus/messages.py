"""Typed message envelopes for distributed coordination.

Parity with the reference's `distributed/messages.go`: message/status/priority
constants (`:11-50`), topics (`:53-58`), WorkQueueMessage/WorkItem(+Config)
(`:61-108`), ResultMessage/WorkResult/DiscoveredPage (`:111-140`),
StatusMessage (`:143-156`), ControlMessage (`:159-166`), constructors with
trace-ID generation (`:179-241`), and `Validate()` on every type (`:255-333`).
"""

from __future__ import annotations

import secrets
import string
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Dict, List, Optional

from ..datamodel.post import format_time, parse_time
from ..state.datamodels import new_id, utcnow

# --- message types (`messages.go:11-29`) -----------------------------------
MSG_WORK_ITEM = "work_item"
MSG_POISON_PILL = "poison_pill"
MSG_WORK_RESULT = "work_result"
MSG_DISCOVERED_PAGES = "discovered_pages"
MSG_HEARTBEAT = "heartbeat"
MSG_WORKER_STARTED = "worker_started"
MSG_WORKER_STOPPING = "worker_stopping"
MSG_PAUSE = "pause"
MSG_RESUME = "resume"
MSG_STOP = "stop"
# New in the TPU build: record batches for the inference worker.
MSG_RECORD_BATCH = "record_batch"
MSG_INFERENCE_RESULT = "inference_result"
# Chaos injection (`loadgen/chaos.py`): a fault the load harness is about
# to apply (kill/stall/wedge a worker, delay/drop/poison bus traffic).
MSG_CHAOS_FAULT = "chaos_fault"
# Media/ASR serving (`media/`): crawled audio refs bound for the batched
# Whisper worker, and the transcripts it sends back.
MSG_AUDIO_BATCH = "audio_batch"
MSG_TRANSCRIPT = "transcript"
# Distributed tracing (`utils/trace.py` SpanExporter -> the
# orchestrator's TraceCollector): a bounded batch of completed spans one
# worker ships so cross-process traces can be assembled at /dtraces.
MSG_SPAN_BATCH = "span_batch"
# Watchtower alerting (`utils/alerts.py` via `orchestrator/watchtower.py`):
# a rule's firing/resolved lifecycle transition, announced fleet-wide.
MSG_ALERT = "alert"
# Streaming clustering (`cluster/`): the ClusterWorker's periodic
# centroid-state announcement — sizes, inertia trend, under-populated
# cluster ids, and a bounded channel→cluster map the orchestrator's
# cluster-guided frontier prioritization consumes.
MSG_CLUSTER_UPDATE = "cluster_update"

# --- status values (`messages.go:32-43`) -----------------------------------
STATUS_SUCCESS = "success"
STATUS_ERROR = "error"
STATUS_PARTIAL = "partial"
STATUS_RETRY = "retry"

WORKER_ACTIVE = "active"
WORKER_IDLE = "idle"
WORKER_BUSY = "busy"
WORKER_ERROR = "error"
WORKER_OFFLINE = "offline"

# --- priorities (`messages.go:46-50`) --------------------------------------
PRIORITY_HIGH = 1
PRIORITY_MEDIUM = 3
PRIORITY_LOW = 5

# --- topics (`messages.go:53-58` + TPU extensions) -------------------------
TOPIC_WORK_QUEUE = "crawl-work-queue"
TOPIC_RESULTS = "crawl-results"
TOPIC_WORKER_STATUS = "worker-status"
TOPIC_ORCHESTRATOR = "orchestrator-commands"
TOPIC_INFERENCE_BATCHES = "tpu-inference-batches"
TOPIC_INFERENCE_RESULTS = "tpu-inference-results"
# Job scheduling commands (schedule/delete) to a `--mode job` service — the
# bus transport replacing the reference's Dapr service-invocation handlers
# (`dapr/job.go:81-95`).
TOPIC_JOBS = "job-commands"
# Chaos-injection announcements from the load harness (`loadgen/chaos.py`):
# every applied fault is published here so distributed targets (and the
# flight recorder on each) can see cause next to effect.
TOPIC_CHAOS = "chaos-commands"
# Media/ASR serving (`media/`): the crawl-side MediaBridge publishes
# AudioBatchMessages here (pull-enabled on serving brokers, exactly like
# the inference topic — a dead ASR worker's frames must requeue), and the
# ASR worker answers with TranscriptMessages on the transcripts topic
# (fan-out: the re-entry hop and any observer subscribe).
TOPIC_MEDIA_BATCHES = "tpu-media-batches"
TOPIC_TRANSCRIPTS = "tpu-transcripts"
# Span export (`SpanBatchMessage`): fan-out like worker-status — the
# orchestrator's TraceCollector subscribes; a missed batch degrades one
# trace's completeness, never correctness, so no pull/ack machinery.
TOPIC_SPANS = "tpu-spans"
# Alert announcements (`AlertMessage`): the watchtower publishes every
# firing/resolved transition here so operators' tools (tools/watch.py, a
# future autoscaler) can react without scraping /alerts.  Fan-out like
# chaos/status — a missed announcement degrades promptness, never the
# /alerts state, so no pull/ack machinery.
TOPIC_ALERTS = "tpu-alerts"
# Cluster-state announcements (`ClusterUpdateMessage`): the streaming
# ClusterWorker publishes its centroid summary here after checkpoints so
# the orchestrator can prioritize frontier pages whose seed posts landed
# in under-populated clusters (cluster-guided snowball).  Fan-out like
# alerts/status — a missed update degrades prioritization freshness,
# never correctness, so no pull/ack machinery.
TOPIC_CLUSTERS = "tpu-clusters"

VALID_PLATFORMS = ("telegram", "youtube")

# Tenant provenance (ISSUE 17): every record batch, audio frame, and
# transcript carries a ``tenant`` label naming the workload that paid for
# it.  Frames minted before the label existed (spooled bytes, outbox
# replays) decode to this documented default, so attribution never breaks
# decodability — an unlabeled frame is "the default tenant's", loudly
# visible as such on /tenants and gateable via ``max_unattributed_share``.
DEFAULT_TENANT = "default"


def normalize_tenant(value: Any) -> str:
    """Fold falsy / non-string tenant values to ``DEFAULT_TENANT``."""
    if not isinstance(value, str) or not value.strip():
        return DEFAULT_TENANT
    return value.strip()

_ALPHANUM = string.ascii_letters + string.digits


def _rand(n: int) -> str:
    return "".join(secrets.choice(_ALPHANUM) for _ in range(n))


def new_trace_id() -> str:
    """`messages.go:239-241`."""
    return "trace_" + utcnow().strftime("%Y%m%d%H%M%S") + "_" + _rand(8)


def new_work_item_id() -> str:
    """`messages.go:233-236`."""
    return "work_" + utcnow().strftime("%Y%m%d%H%M%S") + "_" + _rand(6)


def pubsub_topics() -> List[str]:
    """`messages.go:169-176` + TPU topics."""
    return [TOPIC_WORK_QUEUE, TOPIC_RESULTS, TOPIC_WORKER_STATUS,
            TOPIC_ORCHESTRATOR, TOPIC_INFERENCE_BATCHES,
            TOPIC_INFERENCE_RESULTS, TOPIC_JOBS, TOPIC_CHAOS,
            TOPIC_MEDIA_BATCHES, TOPIC_TRANSCRIPTS, TOPIC_SPANS,
            TOPIC_ALERTS, TOPIC_CLUSTERS]


def _opt_time(value: Any) -> Optional[str]:
    return format_time(value) if value is not None else None


@dataclass
class WorkItemConfig:
    """Crawl config carried inside a work item (`messages.go:89-108`)."""

    storage_root: str = ""
    concurrency: int = 1
    timeout: int = 30
    min_post_date: Optional[datetime] = None
    post_recency: Optional[datetime] = None
    date_between_min: Optional[datetime] = None
    date_between_max: Optional[datetime] = None
    sample_size: int = 0
    max_comments: int = -1
    max_posts: int = -1
    max_depth: int = 0
    max_pages: int = 0
    min_users: int = 0
    crawl_label: str = ""
    skip_media_download: bool = False
    youtube_api_key: str = ""
    sampling_method: str = ""
    min_channel_videos: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "storage_root": self.storage_root,
            "concurrency": self.concurrency,
            "timeout": self.timeout,
            "min_post_date": _opt_time(self.min_post_date),
            "post_recency": _opt_time(self.post_recency),
            "date_between_min": _opt_time(self.date_between_min),
            "date_between_max": _opt_time(self.date_between_max),
            "sample_size": self.sample_size,
            "max_comments": self.max_comments,
            "max_posts": self.max_posts,
            "max_depth": self.max_depth,
            "max_pages": self.max_pages,
            "min_users": self.min_users,
            "crawl_label": self.crawl_label,
            "skip_media_download": self.skip_media_download,
            "youtube_api_key": self.youtube_api_key,
            "sampling_method": self.sampling_method,
            "min_channel_videos": self.min_channel_videos,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkItemConfig":
        return cls(
            storage_root=d.get("storage_root", "") or "",
            concurrency=int(d.get("concurrency") or 1),
            timeout=int(d.get("timeout") or 30),
            min_post_date=parse_time(d.get("min_post_date")),
            post_recency=parse_time(d.get("post_recency")),
            date_between_min=parse_time(d.get("date_between_min")),
            date_between_max=parse_time(d.get("date_between_max")),
            sample_size=int(d.get("sample_size") or 0),
            max_comments=int(d.get("max_comments") if d.get("max_comments") is not None else -1),
            max_posts=int(d.get("max_posts") if d.get("max_posts") is not None else -1),
            max_depth=int(d.get("max_depth") or 0),
            max_pages=int(d.get("max_pages") or 0),
            min_users=int(d.get("min_users") or 0),
            crawl_label=d.get("crawl_label", "") or "",
            skip_media_download=bool(d.get("skip_media_download") or False),
            youtube_api_key=d.get("youtube_api_key", "") or "",
            sampling_method=d.get("sampling_method", "") or "",
            min_channel_videos=int(d.get("min_channel_videos") or 0),
        )


@dataclass
class WorkItem:
    """A single crawl task (`messages.go:71-86`)."""

    id: str = ""
    url: str = ""
    depth: int = 0
    crawl_id: str = ""
    platform: str = ""
    config: WorkItemConfig = field(default_factory=WorkItemConfig)
    parent_id: str = ""
    retry_count: int = 0
    assigned_to: str = ""
    created_at: Optional[datetime] = None
    assigned_at: Optional[datetime] = None
    deadline: Optional[datetime] = None
    metadata: Dict[str, Any] = field(default_factory=dict)
    trace_id: str = ""

    @classmethod
    def new(cls, url: str, depth: int, parent_id: str, crawl_id: str,
            platform: str, config: WorkItemConfig) -> "WorkItem":
        """`messages.go:179-192`."""
        return cls(id=new_work_item_id(), url=url, depth=depth,
                   parent_id=parent_id, crawl_id=crawl_id, platform=platform,
                   config=config, created_at=utcnow(), trace_id=new_trace_id())

    def validate(self) -> None:
        """`messages.go:255-269`."""
        if not self.id:
            raise ValueError("work item ID cannot be empty")
        if not self.url:
            raise ValueError("work item URL cannot be empty")
        if not self.platform:
            raise ValueError("work item platform cannot be empty")
        if self.platform not in VALID_PLATFORMS:
            raise ValueError(f"unsupported platform: {self.platform}")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "url": self.url,
            "depth": self.depth,
            "crawl_id": self.crawl_id,
            "platform": self.platform,
            "config": self.config.to_dict(),
            "parent_id": self.parent_id,
            "retry_count": self.retry_count,
            "assigned_to": self.assigned_to,
            "created_at": _opt_time(self.created_at),
            "assigned_at": _opt_time(self.assigned_at),
            "deadline": _opt_time(self.deadline),
            "metadata": self.metadata,
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkItem":
        return cls(
            id=d.get("id", "") or "",
            url=d.get("url", "") or "",
            depth=int(d.get("depth") or 0),
            crawl_id=d.get("crawl_id", "") or "",
            platform=d.get("platform", "") or "",
            config=WorkItemConfig.from_dict(d.get("config") or {}),
            parent_id=d.get("parent_id", "") or "",
            retry_count=int(d.get("retry_count") or 0),
            assigned_to=d.get("assigned_to", "") or "",
            created_at=parse_time(d.get("created_at")),
            assigned_at=parse_time(d.get("assigned_at")),
            deadline=parse_time(d.get("deadline")),
            metadata=dict(d.get("metadata") or {}),
            trace_id=d.get("trace_id", "") or "",
        )


@dataclass
class WorkQueueMessage:
    """Work-queue envelope (`messages.go:61-68`)."""

    message_type: str = MSG_WORK_ITEM
    work_item: WorkItem = field(default_factory=WorkItem)
    priority: int = PRIORITY_MEDIUM
    timestamp: Optional[datetime] = None
    ttl_seconds: int = 3600
    trace_id: str = ""

    @classmethod
    def new(cls, item: WorkItem, priority: int = PRIORITY_MEDIUM,
            ttl_seconds: int = 3600) -> "WorkQueueMessage":
        """`messages.go:195-204` — except the envelope INHERITS the work
        item's trace id instead of minting a fresh one, so the dispatch
        span, the delivery span, and the worker's processing spans all
        correlate to one trace (the reference generated an id per envelope
        that nothing ever joined)."""
        return cls(message_type=MSG_WORK_ITEM, work_item=item,
                   priority=priority, timestamp=utcnow(),
                   ttl_seconds=ttl_seconds,
                   trace_id=item.trace_id or new_trace_id())

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "work_item": self.work_item.to_dict(),
            "priority": self.priority,
            "timestamp": _opt_time(self.timestamp),
            "ttl_seconds": self.ttl_seconds,
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkQueueMessage":
        return cls(
            message_type=d.get("message_type", MSG_WORK_ITEM),
            work_item=WorkItem.from_dict(d.get("work_item") or {}),
            priority=int(d.get("priority") or PRIORITY_MEDIUM),
            timestamp=parse_time(d.get("timestamp")),
            ttl_seconds=int(d.get("ttl_seconds") or 3600),
            trace_id=d.get("trace_id", "") or "",
        )

    def expired(self, now: Optional[datetime] = None) -> bool:
        if self.timestamp is None or self.ttl_seconds <= 0:
            return False
        now = now or utcnow()
        return (now - self.timestamp).total_seconds() > self.ttl_seconds


@dataclass
class DiscoveredPage:
    """A newly discovered page (`messages.go:135-140`)."""

    url: str = ""
    parent_id: str = ""
    depth: int = 0
    platform: str = ""

    def validate(self) -> None:
        """`messages.go:289-300`."""
        if not self.url:
            raise ValueError("discovered page URL cannot be empty")
        if not self.platform:
            raise ValueError("discovered page platform cannot be empty")
        if self.depth < 0:
            raise ValueError("discovered page depth cannot be negative")

    def to_dict(self) -> Dict[str, Any]:
        return {"url": self.url, "parent_id": self.parent_id,
                "depth": self.depth, "platform": self.platform}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DiscoveredPage":
        return cls(url=d.get("url", "") or "", parent_id=d.get("parent_id", "") or "",
                   depth=int(d.get("depth") or 0), platform=d.get("platform", "") or "")


@dataclass
class WorkResult:
    """Result of a completed work item (`messages.go:120-132`)."""

    work_item_id: str = ""
    worker_id: str = ""
    status: str = STATUS_SUCCESS
    processed_url: str = ""
    message_count: int = 0
    discovered_pages: List[DiscoveredPage] = field(default_factory=list)
    error: str = ""
    processing_time_s: float = 0.0
    metadata: Dict[str, Any] = field(default_factory=dict)
    completed_at: Optional[datetime] = None
    retry_recommended: bool = False

    def validate(self) -> None:
        """`messages.go:272-286`."""
        if not self.work_item_id:
            raise ValueError("work result WorkItemID cannot be empty")
        if not self.worker_id:
            raise ValueError("work result WorkerID cannot be empty")
        if self.status not in (STATUS_SUCCESS, STATUS_ERROR, STATUS_PARTIAL,
                               STATUS_RETRY):
            raise ValueError(f"invalid status: {self.status}")
        if self.status == STATUS_ERROR and not self.error:
            raise ValueError("error status requires error message")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "work_item_id": self.work_item_id,
            "worker_id": self.worker_id,
            "status": self.status,
            "processed_url": self.processed_url,
            "message_count": self.message_count,
            "discovered_pages": [p.to_dict() for p in self.discovered_pages],
            "error": self.error,
            "processing_time": self.processing_time_s,
            "metadata": self.metadata,
            "completed_at": _opt_time(self.completed_at),
            "retry_recommended": self.retry_recommended,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkResult":
        return cls(
            work_item_id=d.get("work_item_id", "") or "",
            worker_id=d.get("worker_id", "") or "",
            status=d.get("status", STATUS_SUCCESS) or STATUS_SUCCESS,
            processed_url=d.get("processed_url", "") or "",
            message_count=int(d.get("message_count") or 0),
            discovered_pages=[DiscoveredPage.from_dict(p)
                              for p in (d.get("discovered_pages") or [])],
            error=d.get("error", "") or "",
            processing_time_s=float(d.get("processing_time") or 0.0),
            metadata=dict(d.get("metadata") or {}),
            completed_at=parse_time(d.get("completed_at")),
            retry_recommended=bool(d.get("retry_recommended") or False),
        )


@dataclass
class ResultMessage:
    """Results envelope (`messages.go:111-117`)."""

    message_type: str = MSG_WORK_RESULT
    work_result: WorkResult = field(default_factory=WorkResult)
    discovered_pages: List[DiscoveredPage] = field(default_factory=list)
    timestamp: Optional[datetime] = None
    trace_id: str = ""

    @classmethod
    def new(cls, result: WorkResult,
            discovered_pages: Optional[List[DiscoveredPage]] = None,
            trace_id: str = "") -> "ResultMessage":
        """`messages.go:222-230`; pass the originating work item's
        ``trace_id`` so the result leg joins the dispatch leg's trace
        (a fresh id is minted only for untraced callers)."""
        return cls(message_type=MSG_WORK_RESULT, work_result=result,
                   discovered_pages=list(discovered_pages or []),
                   timestamp=utcnow(), trace_id=trace_id or new_trace_id())

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "work_result": self.work_result.to_dict(),
            "discovered_pages": [p.to_dict() for p in self.discovered_pages],
            "timestamp": _opt_time(self.timestamp),
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResultMessage":
        return cls(
            message_type=d.get("message_type", MSG_WORK_RESULT),
            work_result=WorkResult.from_dict(d.get("work_result") or {}),
            discovered_pages=[DiscoveredPage.from_dict(p)
                              for p in (d.get("discovered_pages") or [])],
            timestamp=parse_time(d.get("timestamp")),
            trace_id=d.get("trace_id", "") or "",
        )


@dataclass
class StatusMessage:
    """Worker heartbeat/status (`messages.go:143-156`)."""

    message_type: str = MSG_HEARTBEAT
    worker_id: str = ""
    status: str = WORKER_IDLE
    # "crawl" (default) or "tpu": the orchestrator's registry and the
    # co-scheduling backpressure valve key off this (north-star: crawl and
    # inference shards share one orchestrator).
    worker_type: str = "crawl"
    current_work: Optional[str] = None
    queue_length: int = 0
    resource_usage: Dict[str, Any] = field(default_factory=dict)
    tasks_processed: int = 0
    tasks_success: int = 0
    tasks_error: int = 0
    timestamp: Optional[datetime] = None
    uptime_s: float = 0.0
    trace_id: str = ""

    @classmethod
    def new(cls, worker_id: str, message_type: str, status: str,
            tasks_processed: int = 0, tasks_success: int = 0,
            tasks_error: int = 0, uptime_s: float = 0.0,
            worker_type: str = "crawl") -> "StatusMessage":
        """`messages.go:207-219`."""
        return cls(message_type=message_type, worker_id=worker_id, status=status,
                   worker_type=worker_type,
                   tasks_processed=tasks_processed, tasks_success=tasks_success,
                   tasks_error=tasks_error, timestamp=utcnow(),
                   uptime_s=uptime_s, trace_id=new_trace_id())

    def validate(self) -> None:
        """`messages.go:303-333`."""
        if not self.worker_id:
            raise ValueError("status message WorkerID cannot be empty")
        if self.message_type not in (MSG_HEARTBEAT, MSG_WORKER_STARTED,
                                     MSG_WORKER_STOPPING):
            raise ValueError(f"invalid message type: {self.message_type}")
        if self.status not in (WORKER_ACTIVE, WORKER_IDLE, WORKER_BUSY,
                               WORKER_ERROR, WORKER_OFFLINE):
            raise ValueError(f"invalid status: {self.status}")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "worker_id": self.worker_id,
            "status": self.status,
            "worker_type": self.worker_type,
            "current_work": self.current_work,
            "queue_length": self.queue_length,
            "resource_usage": self.resource_usage,
            "tasks_processed": self.tasks_processed,
            "tasks_success": self.tasks_success,
            "tasks_error": self.tasks_error,
            "timestamp": _opt_time(self.timestamp),
            # Canonical key matches the field name; "uptime" stays as a
            # compat alias so decoders from before the rename still parse
            # (the asymmetry used to drop uptime on any path that decoded
            # with the field name).
            "uptime_s": self.uptime_s,
            "uptime": self.uptime_s,
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StatusMessage":
        return cls(
            message_type=d.get("message_type", MSG_HEARTBEAT),
            worker_id=d.get("worker_id", "") or "",
            status=d.get("status", WORKER_IDLE) or WORKER_IDLE,
            worker_type=d.get("worker_type", "crawl") or "crawl",
            current_work=d.get("current_work"),
            queue_length=int(d.get("queue_length") or 0),
            resource_usage=dict(d.get("resource_usage") or {}),
            tasks_processed=int(d.get("tasks_processed") or 0),
            tasks_success=int(d.get("tasks_success") or 0),
            tasks_error=int(d.get("tasks_error") or 0),
            timestamp=parse_time(d.get("timestamp")),
            # Accept both the canonical key and the legacy alias.
            uptime_s=float(d.get("uptime_s", d.get("uptime")) or 0.0),
            trace_id=d.get("trace_id", "") or "",
        )


@dataclass
class ControlMessage:
    """Control command (`messages.go:159-166`)."""

    message_type: str = MSG_PAUSE
    command: str = ""
    target_id: str = ""  # specific worker ID or "all"
    parameters: Dict[str, Any] = field(default_factory=dict)
    timestamp: Optional[datetime] = None
    trace_id: str = ""

    def validate(self) -> None:
        if self.message_type not in (MSG_PAUSE, MSG_RESUME, MSG_STOP):
            raise ValueError(f"invalid control message type: {self.message_type}")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "command": self.command,
            "target_id": self.target_id,
            "parameters": self.parameters,
            "timestamp": _opt_time(self.timestamp),
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ControlMessage":
        return cls(
            message_type=d.get("message_type", MSG_PAUSE),
            command=d.get("command", "") or "",
            target_id=d.get("target_id", "") or "",
            parameters=dict(d.get("parameters") or {}),
            timestamp=parse_time(d.get("timestamp")),
            trace_id=d.get("trace_id", "") or "",
        )


# Fault actions the chaos controller knows how to apply
# (`loadgen/chaos.py`); `validate()` rejects anything else at decode time
# so a typo'd scenario line fails loudly instead of silently no-opping.
CHAOS_ACTIONS = ("kill", "restart", "down", "stall", "wedge", "delay",
                 "drop", "poison", "flood")


@dataclass
class ChaosMessage:
    """One injected fault, announced on ``TOPIC_CHAOS`` the moment the
    chaos controller applies it (`loadgen/chaos.py`).

    ``at_s``/``until_s`` are offsets from scenario start; point faults
    carry ``until_s=0``.  The envelope exists so distributed targets can
    react to faults they cannot observe locally and so every postmortem
    bundle shows the injected cause next to its effect."""

    message_type: str = MSG_CHAOS_FAULT
    action: str = ""                 # one of CHAOS_ACTIONS
    target_id: str = ""              # worker id or "bus"/"batch"
    at_s: float = 0.0
    until_s: float = 0.0             # 0 = point fault (no window)
    parameters: Dict[str, Any] = field(default_factory=dict)
    timestamp: Optional[datetime] = None
    trace_id: str = ""

    @classmethod
    def new(cls, action: str, target_id: str, at_s: float,
            until_s: float = 0.0,
            parameters: Optional[Dict[str, Any]] = None) -> "ChaosMessage":
        return cls(action=action, target_id=target_id, at_s=at_s,
                   until_s=until_s, parameters=dict(parameters or {}),
                   timestamp=utcnow(), trace_id=new_trace_id())

    def validate(self) -> None:
        if self.message_type != MSG_CHAOS_FAULT:
            raise ValueError(
                f"invalid chaos message type: {self.message_type}")
        if self.action not in CHAOS_ACTIONS:
            raise ValueError(f"unknown chaos action: {self.action}")
        if not self.target_id:
            raise ValueError("chaos message target cannot be empty")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "action": self.action,
            "target_id": self.target_id,
            "at_s": self.at_s,
            "until_s": self.until_s,
            "parameters": self.parameters,
            "timestamp": _opt_time(self.timestamp),
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChaosMessage":
        return cls(
            message_type=d.get("message_type", MSG_CHAOS_FAULT),
            action=d.get("action", "") or "",
            target_id=d.get("target_id", "") or "",
            at_s=float(d.get("at_s") or 0.0),
            until_s=float(d.get("until_s") or 0.0),
            parameters=dict(d.get("parameters") or {}),
            timestamp=parse_time(d.get("timestamp")),
            trace_id=d.get("trace_id", "") or "",
        )


# --- media / ASR serving (`media/`) ----------------------------------------

@dataclass
class AudioRef:
    """One crawled media file bound for transcription.

    ``media_id`` is the platform's stable media identifier (Telegram's
    remote file id) — the dedup key the `ShardedMediaCache` and the
    loadgen gate's reconciliation both speak.  ``path`` is where the
    crawl stored the decoded audio (a 16 kHz PCM wav; codec handling is
    an upstream ffmpeg concern, as in `inference/asr.py`)."""

    media_id: str = ""
    path: str = ""
    channel_name: str = ""
    post_uid: str = ""          # originating post, when known
    duration_s: float = 0.0     # 0 = unknown (the chunker measures)

    def validate(self) -> None:
        if not self.media_id:
            raise ValueError("audio ref media_id cannot be empty")
        if not self.path:
            raise ValueError("audio ref path cannot be empty")

    def to_dict(self) -> Dict[str, Any]:
        return {"media_id": self.media_id, "path": self.path,
                "channel_name": self.channel_name,
                "post_uid": self.post_uid,
                "duration_s": self.duration_s}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AudioRef":
        return cls(
            media_id=d.get("media_id", "") or "",
            path=d.get("path", "") or "",
            channel_name=d.get("channel_name", "") or "",
            post_uid=d.get("post_uid", "") or "",
            duration_s=float(d.get("duration_s") or 0.0),
        )


@dataclass
class AudioBatchMessage:
    """A batch of audio refs on ``TOPIC_MEDIA_BATCHES`` — the media twin
    of the inference topic's `RecordBatch`.  Minted with a trace id at
    birth so the ASR worker's queue-wait/chunk/decode spans correlate to
    the crawl-side dispatch from the first frame."""

    message_type: str = MSG_AUDIO_BATCH
    batch_id: str = ""
    crawl_id: str = ""
    refs: List[AudioRef] = field(default_factory=list)
    created_at: Optional[datetime] = None
    trace_id: str = ""
    tenant: str = DEFAULT_TENANT

    @classmethod
    def new(cls, refs: List[AudioRef], crawl_id: str = "",
            trace_id: str = "",
            tenant: str = DEFAULT_TENANT) -> "AudioBatchMessage":
        return cls(batch_id=new_id(), crawl_id=crawl_id, refs=list(refs),
                   created_at=utcnow(), trace_id=trace_id or new_trace_id(),
                   tenant=normalize_tenant(tenant))

    def validate(self) -> None:
        if self.message_type != MSG_AUDIO_BATCH:
            raise ValueError(
                f"invalid audio batch message type: {self.message_type}")
        if not self.batch_id:
            raise ValueError("audio batch ID cannot be empty")
        if not self.refs:
            raise ValueError("audio batch carries no refs")
        for ref in self.refs:
            ref.validate()

    def __len__(self) -> int:
        return len(self.refs)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "batch_id": self.batch_id,
            "crawl_id": self.crawl_id,
            "refs": [r.to_dict() for r in self.refs],
            "created_at": _opt_time(self.created_at),
            "trace_id": self.trace_id,
            "tenant": self.tenant,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AudioBatchMessage":
        return cls(
            message_type=d.get("message_type", MSG_AUDIO_BATCH),
            batch_id=d.get("batch_id", "") or "",
            crawl_id=d.get("crawl_id", "") or "",
            refs=[AudioRef.from_dict(r) for r in (d.get("refs") or [])
                  if isinstance(r, dict)],
            created_at=parse_time(d.get("created_at")),
            trace_id=d.get("trace_id", "") or "",
            tenant=normalize_tenant(d.get("tenant")),
        )


@dataclass
class TranscriptMessage:
    """One media file's transcript on ``TOPIC_TRANSCRIPTS``.

    ``post_uid`` is DETERMINISTIC (``media:<media_id>``) so the re-entry
    hop through `InferenceBridge` rides the PR-7 dedupe window: an
    at-least-once redelivery or a re-crawl of the same media cannot
    double-count downstream.  ``error`` is non-empty for files that
    failed to decode — failures are explicit rows, never silent gaps.
    Inherits the audio batch's trace id, so one trace spans crawl →
    audio → transcript → embedding."""

    message_type: str = MSG_TRANSCRIPT
    media_id: str = ""
    post_uid: str = ""
    path: str = ""
    channel_name: str = ""
    crawl_id: str = ""
    batch_id: str = ""          # the AudioBatchMessage that carried it
    worker_id: str = ""
    text: str = ""
    tokens: List[int] = field(default_factory=list)
    windows: int = 0            # 30 s windows transcribed
    duration_s: float = 0.0
    error: str = ""
    timestamp: Optional[datetime] = None
    trace_id: str = ""
    tenant: str = DEFAULT_TENANT

    @classmethod
    def new(cls, media_id: str, crawl_id: str = "", batch_id: str = "",
            worker_id: str = "", trace_id: str = "",
            tenant: str = DEFAULT_TENANT, **kw: Any) -> "TranscriptMessage":
        return cls(media_id=media_id, post_uid=f"media:{media_id}",
                   crawl_id=crawl_id, batch_id=batch_id,
                   worker_id=worker_id, timestamp=utcnow(),
                   trace_id=trace_id or new_trace_id(),
                   tenant=normalize_tenant(tenant), **kw)

    def validate(self) -> None:
        if self.message_type != MSG_TRANSCRIPT:
            raise ValueError(
                f"invalid transcript message type: {self.message_type}")
        if not self.media_id:
            raise ValueError("transcript media_id cannot be empty")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "media_id": self.media_id,
            "post_uid": self.post_uid,
            "path": self.path,
            "channel_name": self.channel_name,
            "crawl_id": self.crawl_id,
            "batch_id": self.batch_id,
            "worker_id": self.worker_id,
            "text": self.text,
            "tokens": list(self.tokens),
            "windows": self.windows,
            "duration_s": self.duration_s,
            "error": self.error,
            "timestamp": _opt_time(self.timestamp),
            "trace_id": self.trace_id,
            "tenant": self.tenant,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TranscriptMessage":
        return cls(
            message_type=d.get("message_type", MSG_TRANSCRIPT),
            media_id=d.get("media_id", "") or "",
            post_uid=d.get("post_uid", "") or "",
            path=d.get("path", "") or "",
            channel_name=d.get("channel_name", "") or "",
            crawl_id=d.get("crawl_id", "") or "",
            batch_id=d.get("batch_id", "") or "",
            worker_id=d.get("worker_id", "") or "",
            text=d.get("text", "") or "",
            tokens=[int(t) for t in (d.get("tokens") or [])],
            windows=int(d.get("windows") or 0),
            duration_s=float(d.get("duration_s") or 0.0),
            error=d.get("error", "") or "",
            timestamp=parse_time(d.get("timestamp")),
            trace_id=d.get("trace_id", "") or "",
            tenant=normalize_tenant(d.get("tenant")),
        )


# --- watchtower alerting (`utils/alerts.py`) --------------------------------

ALERT_STATES = ("pending", "firing", "resolved", "inactive")


@dataclass
class AlertMessage:
    """One alert lifecycle transition on ``TOPIC_ALERTS``.

    Published by the orchestrator's watchtower for ``firing`` and
    ``resolved`` transitions (pending/inactive churn stays local to
    ``/alerts``).  ``value`` is the rule's evaluated value at transition
    time (a burn rate, a slope, an aggregate) and ``detail`` carries the
    kind-specific context (fast/slow burn, threshold, matched-series
    count).  The envelope's ``trace_id`` exists for registry uniformity
    (the crawlint BUS contract); alerts are telemetry about the fleet,
    they do not participate in a work item's trace."""

    message_type: str = MSG_ALERT
    rule: str = ""
    kind: str = ""                   # threshold | trend | burn_rate
    series: str = ""
    state: str = ""                  # the state ENTERED (ALERT_STATES)
    prev_state: str = ""
    severity: str = "page"
    value: Optional[float] = None
    detail: Dict[str, Any] = field(default_factory=dict)
    at_wall: float = 0.0             # sender epoch of the transition
    timestamp: Optional[datetime] = None
    trace_id: str = ""

    @classmethod
    def new(cls, rule: str, kind: str, series: str, state: str,
            prev_state: str = "", severity: str = "page",
            value: Optional[float] = None,
            detail: Optional[Dict[str, Any]] = None,
            at_wall: float = 0.0) -> "AlertMessage":
        import time as _time

        return cls(rule=rule, kind=kind, series=series, state=state,
                   prev_state=prev_state, severity=severity, value=value,
                   detail=dict(detail or {}),
                   at_wall=at_wall or _time.time(),
                   timestamp=utcnow(), trace_id=new_trace_id())

    def validate(self) -> None:
        if self.message_type != MSG_ALERT:
            raise ValueError(
                f"invalid alert message type: {self.message_type}")
        if not self.rule:
            raise ValueError("alert message rule cannot be empty")
        if self.state not in ALERT_STATES:
            raise ValueError(f"invalid alert state: {self.state}")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "rule": self.rule,
            "kind": self.kind,
            "series": self.series,
            "state": self.state,
            "prev_state": self.prev_state,
            "severity": self.severity,
            "value": self.value,
            "detail": self.detail,
            "at_wall": self.at_wall,
            "timestamp": _opt_time(self.timestamp),
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AlertMessage":
        value = d.get("value")
        return cls(
            message_type=d.get("message_type", MSG_ALERT),
            rule=d.get("rule", "") or "",
            kind=d.get("kind", "") or "",
            series=d.get("series", "") or "",
            state=d.get("state", "") or "",
            prev_state=d.get("prev_state", "") or "",
            severity=d.get("severity", "page") or "page",
            value=float(value) if value is not None else None,
            detail=dict(d.get("detail") or {}),
            at_wall=float(d.get("at_wall") or 0.0),
            timestamp=parse_time(d.get("timestamp")),
            trace_id=d.get("trace_id", "") or "",
        )


# --- streaming clustering (`cluster/`) --------------------------------------

@dataclass
class ClusterUpdateMessage:
    """The ClusterWorker's periodic centroid-state summary on
    ``TOPIC_CLUSTERS``.

    ``sizes`` is the per-cluster cumulative assignment count (length
    ``k``), ``inertia`` the rolling mean per-vector inertia of recent
    steps, ``underpopulated`` the cluster ids whose share of assignments
    is below the worker's ``min_cluster_fraction`` threshold, and
    ``channel_clusters`` a bounded map of recently-seen channel names to
    the cluster their posts most recently landed in — the join key the
    orchestrator's cluster-guided frontier prioritization uses (a
    frontier page whose channel maps to an under-populated cluster
    dispatches at ``PRIORITY_HIGH``).  The envelope's ``trace_id``
    exists for registry uniformity (the crawlint BUS contract); cluster
    updates are telemetry about the stream, not part of one trace."""

    message_type: str = MSG_CLUSTER_UPDATE
    worker_id: str = ""
    k: int = 0
    step: int = 0                    # mini-batch steps applied so far
    vectors: int = 0                 # embeddings assigned so far
    sizes: List[int] = field(default_factory=list)
    inertia: Optional[float] = None
    underpopulated: List[int] = field(default_factory=list)
    channel_clusters: Dict[str, int] = field(default_factory=dict)
    timestamp: Optional[datetime] = None
    trace_id: str = ""

    @classmethod
    def new(cls, worker_id: str, k: int, step: int = 0, vectors: int = 0,
            sizes: Optional[List[int]] = None,
            inertia: Optional[float] = None,
            underpopulated: Optional[List[int]] = None,
            channel_clusters: Optional[Dict[str, int]] = None
            ) -> "ClusterUpdateMessage":
        return cls(worker_id=worker_id, k=int(k), step=int(step),
                   vectors=int(vectors), sizes=list(sizes or []),
                   inertia=inertia,
                   underpopulated=list(underpopulated or []),
                   channel_clusters=dict(channel_clusters or {}),
                   timestamp=utcnow(), trace_id=new_trace_id())

    def validate(self) -> None:
        if self.message_type != MSG_CLUSTER_UPDATE:
            raise ValueError(
                f"invalid cluster update message type: {self.message_type}")
        if not self.worker_id:
            raise ValueError("cluster update worker_id cannot be empty")
        if self.k <= 0:
            raise ValueError("cluster update k must be positive")
        if self.sizes and len(self.sizes) != self.k:
            raise ValueError(
                f"cluster update carries {len(self.sizes)} sizes for k="
                f"{self.k}")
        for c in self.underpopulated:
            if not 0 <= int(c) < self.k:
                raise ValueError(f"underpopulated cluster id {c} out of "
                                 f"range for k={self.k}")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "worker_id": self.worker_id,
            "k": self.k,
            "step": self.step,
            "vectors": self.vectors,
            "sizes": self.sizes,
            "inertia": self.inertia,
            "underpopulated": self.underpopulated,
            "channel_clusters": self.channel_clusters,
            "timestamp": _opt_time(self.timestamp),
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ClusterUpdateMessage":
        inertia = d.get("inertia")
        return cls(
            message_type=d.get("message_type", MSG_CLUSTER_UPDATE),
            worker_id=d.get("worker_id", "") or "",
            k=int(d.get("k") or 0),
            step=int(d.get("step") or 0),
            vectors=int(d.get("vectors") or 0),
            sizes=[int(s) for s in (d.get("sizes") or [])],
            inertia=float(inertia) if inertia is not None else None,
            underpopulated=[int(c) for c in (d.get("underpopulated") or [])],
            channel_clusters={str(ch): int(c) for ch, c in
                              (d.get("channel_clusters") or {}).items()},
            timestamp=parse_time(d.get("timestamp")),
            trace_id=d.get("trace_id", "") or "",
        )


# --- distributed tracing (`utils/trace.py` -> orchestrator) -----------------

@dataclass
class SpanBatchMessage:
    """A bounded batch of completed spans on ``TOPIC_SPANS``.

    ``spans`` carries `utils.trace.Span.to_dict()` rows (name, trace_id,
    span_id, parent_id, start_wall, duration_ms, attrs) — every
    ``start_wall`` is on the SENDER's wall clock; the collector corrects
    it with the per-worker offset estimated from heartbeat send/receive
    walls (``sent_wall`` here is the publish-side fallback estimator for
    workers that have not heartbeated yet).  ``dropped`` counts spans
    NOT shipped since the previous batch (ring eviction, sampling, the
    per-batch bound), so assembled traces can say how lossy they are.

    The envelope's own ``trace_id`` exists for registry uniformity (the
    crawlint BUS checker's contract); span batches are telemetry about
    traces, they do not participate in one.
    """

    message_type: str = MSG_SPAN_BATCH
    worker_id: str = ""
    sent_wall: float = 0.0              # sender epoch at publish
    spans: List[Dict[str, Any]] = field(default_factory=list)
    dropped: int = 0
    timestamp: Optional[datetime] = None
    trace_id: str = ""

    @classmethod
    def new(cls, worker_id: str, spans: List[Dict[str, Any]],
            dropped: int = 0) -> "SpanBatchMessage":
        import time as _time

        return cls(worker_id=worker_id, sent_wall=_time.time(),
                   spans=list(spans), dropped=int(dropped),
                   timestamp=utcnow(), trace_id=new_trace_id())

    def validate(self) -> None:
        if self.message_type != MSG_SPAN_BATCH:
            raise ValueError(
                f"invalid span batch message type: {self.message_type}")
        if not self.worker_id:
            raise ValueError("span batch worker_id cannot be empty")
        for s in self.spans:
            if not isinstance(s, dict) or not s.get("name") \
                    or not s.get("trace_id"):
                raise ValueError(
                    "span batch rows need at least name + trace_id")

    def __len__(self) -> int:
        return len(self.spans)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "message_type": self.message_type,
            "worker_id": self.worker_id,
            "sent_wall": self.sent_wall,
            "spans": self.spans,
            "dropped": self.dropped,
            "timestamp": _opt_time(self.timestamp),
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SpanBatchMessage":
        return cls(
            message_type=d.get("message_type", MSG_SPAN_BATCH),
            worker_id=d.get("worker_id", "") or "",
            sent_wall=float(d.get("sent_wall") or 0.0),
            spans=[s for s in (d.get("spans") or [])
                   if isinstance(s, dict)],
            dropped=int(d.get("dropped") or 0),
            timestamp=parse_time(d.get("timestamp")),
            trace_id=d.get("trace_id", "") or "",
        )
