"""In-memory message bus with at-least-once delivery semantics.

Parity with the reference's pubsub contract (`distributed/pubsub.go:149-254`):
- a payload that fails to decode is dropped (no retry — it will never parse);
- a handler that raises is retried up to `max_redeliveries` times (the Dapr
  "retry" status), then the message is dropped to the dead-letter list;
- handlers per topic, registered before or after start.

Used exactly like the reference's in-memory integration pubsub
(`distributed/integration_test.go:109-180`) in tests, and as the standalone
single-process bus in production modes.  Cross-host transport is
`bus/grpc_bus.py`.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import resilience, trace
from .payload import serialize_payload

logger = logging.getLogger("dct.bus")

Handler = Callable[[Dict[str, Any]], None]


class InMemoryBus:
    """Topic-based pubsub with retry-on-handler-error."""

    def __init__(self, max_redeliveries: int = 3, retry_delay_s: float = 0.0,
                 sync: bool = True):
        """sync=True delivers inline on publish (deterministic for tests and
        single-process modes); sync=False uses a background dispatch thread."""
        self.max_redeliveries = max_redeliveries
        self.retry_delay_s = retry_delay_s
        self.sync = sync
        # Redelivery schedule declared through the shared policy layer
        # (utils/resilience.py): fixed delay (multiplier 1) preserves the
        # historical behavior; FLOOD_WAIT-style ``retry_after_s`` hints
        # on handler errors are honoured, capped.
        self._retry = resilience.RetryPolicy(
            max_attempts=max_redeliveries + 1, base_delay_s=retry_delay_s,
            max_delay_s=max(retry_delay_s, 1.0), multiplier=1.0,
            jitter=0.0, retry_after_cap_s=2.0)
        self._handlers: Dict[str, List[Handler]] = {}
        self._lock = threading.RLock()
        self._queue: "queue.Queue[Tuple[str, bytes]]" = queue.Queue()
        self._dead_letters: List[Tuple[str, Dict[str, Any], str]] = []
        self._published_count: Dict[str, int] = {}
        self._delivered_count: Dict[str, int] = {}
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # --- wiring -----------------------------------------------------------
    def subscribe(self, topic: str, handler: Handler) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def start(self) -> None:
        """Start async dispatch (no-op in sync mode)."""
        if self.sync or self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="dct-bus", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        # At-least-once: deliver anything still queued before shutting down.
        while True:
            try:
                topic, data = self._queue.get_nowait()
            except queue.Empty:
                break
            self._deliver(topic, data)

    # --- publish ----------------------------------------------------------
    def publish(self, topic: str, payload: Any) -> None:
        """Publish a dict (JSON-serialized) or raw bytes to a topic.

        Trace propagation: a dict payload carrying a ``trace_id`` is
        stamped with the publisher's open span as ``parent_span``
        (`utils/trace.inject`), so the delivery span on the consumer side
        links back to the publish site across the hop."""
        payload = trace.inject(payload)
        data = serialize_payload(payload)
        with self._lock:
            self._published_count[topic] = self._published_count.get(topic, 0) + 1
        if self.sync:
            self._deliver(topic, data)
        else:
            self._queue.put((topic, data))

    # --- delivery ---------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while self._running:
            try:
                topic, data = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            self._deliver(topic, data)

    def _deliver(self, topic: str, data: bytes) -> None:
        try:
            payload = json.loads(data.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            # Undecodable payloads are not retried (`pubsub.go:157-165`).
            logger.error("dropping undecodable message on %s: %s", topic, e)
            return
        with self._lock:
            handlers = list(self._handlers.get(topic, []))
        # The delivery hop is a span of the envelope's trace (no-op for
        # untraced payloads): handler spans nest under it, so one trace
        # walks publish -> deliver -> handler stages.
        with trace.payload_span("bus.deliver", payload, topic=topic,
                                transport="inmemory"):
            for handler in handlers:
                delivered, last_err = True, ""
                try:
                    # Handler error -> retry (`pubsub.go:166-171`), via
                    # the shared policy layer.
                    resilience.retry_call(handler, payload,
                                          retry=self._retry,
                                          op=f"bus.inmemory.{topic}")
                except Exception as e:
                    delivered, last_err = False, str(e)
                with self._lock:
                    if delivered:
                        self._delivered_count[topic] = \
                            self._delivered_count.get(topic, 0) + 1
                    else:
                        self._dead_letters.append((topic, payload, last_err))

    # --- introspection (tests + metrics) ----------------------------------
    @property
    def dead_letters(self) -> List[Tuple[str, Dict[str, Any], str]]:
        with self._lock:
            return list(self._dead_letters)

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                "published": dict(self._published_count),
                "delivered": dict(self._delivered_count),
                "dead_lettered": {"total": len(self._dead_letters)},
            }

    def drain(self, timeout_s: float = 2.0) -> bool:
        """Wait for the async queue to empty (tests)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._queue.empty():
                return True
            time.sleep(0.005)
        return self._queue.empty()
