"""gRPC transport: the DCN leg of the message bus.

The reference talked to its sidecar over gRPC with 201 MB frames
(`state/daprstate.go:104-133`); here the bus itself is the service.  Uses
gRPC generic handlers with raw-bytes (de)serializers — no protoc codegen —
carrying the same JSON payloads as InMemoryBus plus codec frames for record
batches.  Three RPCs:

- Publish (unary): topic + payload -> ack
- Pull (server-streaming): workers pull frames for a topic, giving
  backpressure-aware feeding of the TPU worker
- Ack (unary): per-delivery acknowledgement closing the at-least-once loop

Delivery guarantees (parity with `distributed/pubsub.go:157-254`, which
relied on the broker redelivering on handler error): every pulled frame
carries a delivery ID and stays "in flight" on the server until acked.
Unacked frames are requeued when the pulling stream dies, when the client
nacks (handler exhausted its retries), or when the ack deadline passes —
so a worker crash mid-handler no longer loses work.  A frame redelivered
more than ``max_attempts`` times is dead-lettered (logged + dropped),
bounding poison-message loops.

Tensor traffic never rides this bus: on-slice collectives are XLA/ICI
(`parallel/`).  This is coordination + record streaming only.
"""

from __future__ import annotations

import inspect
import json
import logging
import queue
import threading
import time
import uuid
from concurrent import futures
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import grpc

from ..utils import resilience, trace
from .payload import serialize_payload

logger = logging.getLogger("dct.bus.grpc")

SERVICE_NAME = "dct.bus.Bus"
MAX_FRAME_BYTES = 201 * 1024 * 1024  # parity: daprstate.go:108-110

DEFAULT_ACK_TIMEOUT_S = 300.0
DEFAULT_MAX_ATTEMPTS = 5

_TOPIC_SEP = b"\x00"


def _encode_envelope(topic: str, payload: bytes) -> bytes:
    return topic.encode("utf-8") + _TOPIC_SEP + payload


def _decode_envelope(data: bytes) -> tuple:
    topic, _, payload = data.partition(_TOPIC_SEP)
    return topic.decode("utf-8"), payload


def _identity(b: bytes) -> bytes:
    return b


@dataclass
class _QueuedFrame:
    payload: bytes
    attempts: int = 0


@dataclass
class _Inflight:
    payload: bytes
    attempts: int
    deadline: float
    stream_id: int


@dataclass
class _TopicQueue:
    """Pull queue + in-flight ledger for one topic."""

    q: "queue.Queue[_QueuedFrame]" = field(default_factory=queue.Queue)
    inflight: Dict[str, _Inflight] = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock)


class GrpcBusServer:
    """Hosts topics; local subscribers receive published payloads, and remote
    pullers stream queued record batches with per-delivery acks."""

    def __init__(self, address: str = "127.0.0.1:50551", max_workers: int = 8,
                 ack_timeout_s: float = DEFAULT_ACK_TIMEOUT_S,
                 max_attempts: int = DEFAULT_MAX_ATTEMPTS):
        self.address = address
        self.ack_timeout_s = ack_timeout_s
        self.max_attempts = max_attempts
        # Local-handler delivery policy: the backoff/attempt schedule is
        # declared ONCE (utils/resilience.py) instead of hand-rolled per
        # loop; a handler raising a FLOOD_WAIT-style error (carrying
        # ``retry_after_s``) gets its server-directed backoff honoured,
        # capped so one hostile hint can't park a topic's dispatch thread.
        self._local_retry = resilience.RetryPolicy(
            max_attempts=max_attempts, base_delay_s=0.05, max_delay_s=0.5,
            jitter=0.0, retry_after_cap_s=2.0)
        self._handlers: Dict[str, list] = {}
        self._pull_queues: Dict[str, _TopicQueue] = {}
        self._lock = threading.RLock()
        self._stream_counter = 0
        self.dead_letters = 0
        # Local-subscriber dispatch: per-topic queue + worker thread, so
        # handlers run OFF the gRPC thread and get the same bounded-retry
        # treatment as pulled frames (`distributed/pubsub.go:157-171`
        # retried every subscriber on error; inline-and-swallow was
        # at-most-once).
        self._local_queues: Dict[str, "queue.Queue"] = {}
        self._local_threads: Dict[str, threading.Thread] = {}
        self._local_idle = threading.Condition()
        self._local_inflight = 0
        self._stop = threading.Event()
        self._sweeper: Optional[threading.Thread] = None
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.max_receive_message_length", MAX_FRAME_BYTES),
                     ("grpc.max_send_message_length", MAX_FRAME_BYTES)])
        handlers = {
            "Publish": grpc.unary_unary_rpc_method_handler(
                self._publish_rpc, request_deserializer=_identity,
                response_serializer=_identity),
            "Pull": grpc.unary_stream_rpc_method_handler(
                self._pull_rpc, request_deserializer=_identity,
                response_serializer=_identity),
            "Ack": grpc.unary_unary_rpc_method_handler(
                self._ack_rpc, request_deserializer=_identity,
                response_serializer=_identity),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))
        self.bound_port = self._server.add_insecure_port(address)

    # --- service ----------------------------------------------------------
    def _publish_rpc(self, request: bytes, context) -> bytes:
        topic, payload = _decode_envelope(request)
        with self._lock:
            has_handlers = bool(self._handlers.get(topic))
            tq = self._pull_queues.get(topic)
            lq = self._local_queues.get(topic) if has_handlers else None
        if tq is not None:
            tq.q.put(_QueuedFrame(payload))
        if lq is not None:
            try:
                decoded = json.loads(payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                # Undecodable payloads are dropped, never retried.
                logger.error("dropping undecodable message on %s", topic)
                return b"ok"
            with self._local_idle:
                self._local_inflight += 1
            lq.put(decoded)
        return b"ok"

    def _local_dispatch_loop(self, topic: str, lq: "queue.Queue") -> None:
        # Keeps draining after _stop until the queue is empty: a Publish we
        # answered b"ok" to must reach local handlers even across close()
        # (retry backoffs short-circuit once _stop is set).
        while True:
            try:
                decoded = lq.get(timeout=0.25)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                with self._lock:
                    handlers = list(self._handlers.get(topic, []))
                with trace.payload_span("bus.deliver", decoded, topic=topic,
                                        transport="grpc-local"):
                    for handler in handlers:
                        try:
                            # Stop-event-aware waits: close() never blocks
                            # on a backoff mid-drain.
                            resilience.retry_call(
                                handler, decoded, retry=self._local_retry,
                                op=f"bus.local.{topic}", stop=self._stop)
                        except Exception:
                            self._count_dead_letter()
                            logger.error(
                                "dead-lettering local delivery on %s after "
                                "%d attempts", topic, self.max_attempts)
            finally:
                with self._local_idle:
                    self._local_inflight -= 1
                    if self._local_inflight == 0:
                        self._local_idle.notify_all()

    def flush_local(self, timeout_s: float = 5.0) -> bool:
        """Block until every queued local delivery has been dispatched
        (tests / orderly shutdown).  Returns False on timeout."""
        with self._local_idle:
            return self._local_idle.wait_for(
                lambda: self._local_inflight == 0, timeout=timeout_s)

    def _sweep_loop(self) -> None:
        # Dedicated sweeper: ack deadlines fire even with no active puller
        # (a blocked or absent consumer must not pin frames in flight).
        interval = max(0.05, min(1.0, self.ack_timeout_s / 4.0))
        while not self._stop.wait(interval):
            with self._lock:
                topics = list(self._pull_queues.items())
            for topic, tq in topics:
                self._sweep_expired(topic, tq)

    def _count_dead_letter(self) -> None:
        # Called from pull-stream threads, the sweeper, and local dispatch
        # threads concurrently — += on an int is not atomic.
        with self._lock:
            self.dead_letters += 1

    def _requeue_or_drop(self, topic: str, tq: _TopicQueue,
                         delivery_id: str, inf: _Inflight) -> None:
        """inf has been removed from the inflight map by the caller."""
        if inf.attempts + 1 >= self.max_attempts:
            self._count_dead_letter()
            logger.error(
                "dead-lettering frame on %s after %d attempts (id=%s)",
                topic, inf.attempts + 1, delivery_id)
            return
        tq.q.put(_QueuedFrame(inf.payload, attempts=inf.attempts + 1))

    def _sweep_expired(self, topic: str, tq: _TopicQueue) -> None:
        now = time.monotonic()
        with tq.lock:
            expired = [(d, i) for d, i in tq.inflight.items()
                       if i.deadline <= now]
            for d, _ in expired:
                del tq.inflight[d]
        for d, inf in expired:
            logger.warning("ack timeout on %s (id=%s); requeueing", topic, d)
            self._requeue_or_drop(topic, tq, d, inf)

    def _pull_rpc(self, request: bytes, context) -> Iterator[bytes]:
        topic = request.decode("utf-8")
        with self._lock:
            tq = self._pull_queues.setdefault(topic, _TopicQueue())
            self._stream_counter += 1
            stream_id = self._stream_counter
        try:
            while context.is_active():
                self._sweep_expired(topic, tq)
                # Pop and register in-flight ATOMICALLY under tq.lock: a
                # frame popped but not yet registered would be invisible
                # to pending_count(), letting drain() declare the broker
                # empty while a frame is mid-handoff.
                with tq.lock:
                    try:
                        frame = tq.q.get_nowait()
                    except queue.Empty:
                        frame = None
                    else:
                        delivery_id = uuid.uuid4().hex
                        tq.inflight[delivery_id] = _Inflight(
                            frame.payload, frame.attempts,
                            time.monotonic() + self.ack_timeout_s,
                            stream_id)
                if frame is None:
                    time.sleep(0.05)
                    continue
                try:
                    yield delivery_id.encode("ascii") + _TOPIC_SEP + \
                        frame.payload
                except BaseException:
                    # Stream cancelled between pop and consume: requeue so
                    # the batch isn't lost (at-least-once for pulled frames).
                    with tq.lock:
                        inf = tq.inflight.pop(delivery_id, None)
                    if inf is not None:
                        tq.q.put(_QueuedFrame(inf.payload, inf.attempts))
                    raise
        finally:
            # Stream gone (worker died / disconnected): everything this
            # stream delivered but never acked goes back on the queue.
            with tq.lock:
                orphaned = [(d, i) for d, i in tq.inflight.items()
                            if i.stream_id == stream_id]
                for d, _ in orphaned:
                    del tq.inflight[d]
            for d, inf in orphaned:
                logger.info("stream for %s closed with unacked frame "
                            "(id=%s); requeueing", topic, d)
                self._requeue_or_drop(topic, tq, d, inf)

    def _ack_rpc(self, request: bytes, context) -> bytes:
        topic_b, _, rest = request.partition(_TOPIC_SEP)
        delivery_b, _, status = rest.partition(_TOPIC_SEP)
        topic = topic_b.decode("utf-8")
        delivery_id = delivery_b.decode("ascii")
        with self._lock:
            tq = self._pull_queues.get(topic)
        if tq is None:
            return b"unknown-topic"
        with tq.lock:
            inf = tq.inflight.pop(delivery_id, None)
        if inf is None:
            return b"unknown-delivery"  # already requeued/expired
        if status != b"ok":
            self._requeue_or_drop(topic, tq, delivery_id, inf)
        return b"ok"

    # --- local wiring -----------------------------------------------------
    def subscribe(self, topic: str, handler: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)
            if topic not in self._local_queues:
                lq: "queue.Queue" = queue.Queue()
                self._local_queues[topic] = lq
                t = threading.Thread(
                    target=self._local_dispatch_loop, args=(topic, lq),
                    daemon=True, name=f"dct-bus-local-{topic}")
                self._local_threads[topic] = t
                t.start()

    def publish(self, topic: str, payload: Any) -> None:
        """Local publish: same fan-out as a remote Publish RPC, so the host
        process (e.g. the orchestrator) can use the server as its bus."""
        payload = trace.inject(payload)
        self._publish_rpc(_encode_envelope(topic, serialize_payload(payload)),
                          None)

    def enable_pull(self, topic: str) -> None:
        with self._lock:
            self._pull_queues.setdefault(topic, _TopicQueue())

    def pending_count(self, topic: str) -> int:
        """Queued + in-flight frames (observability / tests)."""
        with self._lock:
            tq = self._pull_queues.get(topic)
        if tq is None:
            return 0
        with tq.lock:
            return tq.q.qsize() + len(tq.inflight)

    def drain(self, timeout_s: float = 30.0,
              poll_s: float = 0.2) -> bool:
        """Block until every pull topic is empty (queued AND in-flight),
        or the timeout expires; returns True when fully drained.

        A broker-hosting process that exits the moment ITS work is done
        (the orchestrator after crawl completion) takes every undelivered
        frame down with it — consumers that were still warming up lose
        their batches.  Call this before close().
        """
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                topics = list(self._pull_queues)
            remaining = {t: self.pending_count(t) for t in topics}
            remaining = {t: n for t, n in remaining.items() if n}
            if not remaining:
                return True
            if time.monotonic() >= deadline:
                logger.warning(
                    "bus drain timed out with frames pending: %s", remaining)
                return False
            time.sleep(poll_s)

    def start(self) -> None:
        self._server.start()
        self._sweeper = threading.Thread(target=self._sweep_loop,
                                         daemon=True, name="dct-bus-sweeper")
        self._sweeper.start()
        logger.info("bus server listening on %s", self.address)

    def close(self, grace: float = 0.5) -> None:
        # stop() returns immediately; in-flight Publish RPCs keep running
        # for up to `grace`.  Wait for full termination BEFORE setting
        # _stop, or a dispatch thread could exit on an empty queue while an
        # in-flight RPC is about to enqueue a frame we already acked b"ok".
        self._server.stop(grace).wait(grace + 5.0)
        self._stop.set()          # dispatch loops drain, then exit
        if not self.flush_local(timeout_s=max(grace, 5.0)):
            with self._local_idle:
                remaining = self._local_inflight
            logger.error("bus closed with %d undelivered local "
                         "message(s)", remaining)
        if self._sweeper is not None:
            self._sweeper.join(timeout=2.0)
        for t in self._local_threads.values():
            t.join(timeout=2.0)


class GrpcBusClient:
    """Publishes payloads / pulls record-batch frames from a GrpcBusServer."""

    def __init__(self, target: str = "127.0.0.1:50551"):
        self.target = target
        self._channel = grpc.insecure_channel(
            target,
            options=[("grpc.max_receive_message_length", MAX_FRAME_BYTES),
                     ("grpc.max_send_message_length", MAX_FRAME_BYTES)])
        self._publish = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Publish", request_serializer=_identity,
            response_deserializer=_identity)
        self._pull = self._channel.unary_stream(
            f"/{SERVICE_NAME}/Pull", request_serializer=_identity,
            response_deserializer=_identity)
        self._ack = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Ack", request_serializer=_identity,
            response_deserializer=_identity)

    def publish(self, topic: str, payload: Any) -> None:
        # Same propagation seam as InMemoryBus.publish: the envelope
        # crosses a process boundary here, which is exactly the hop the
        # parent_span stamp exists for.
        payload = trace.inject(payload)
        self._publish(_encode_envelope(topic, serialize_payload(payload)))

    def publish_frame(self, topic: str, frame: bytes) -> None:
        """Publish an already-encoded codec frame (record batches)."""
        self._publish(_encode_envelope(topic, frame))

    def pull(self, topic: str) -> Iterator[Tuple[str, bytes]]:
        """Server-streaming pull; yields (delivery_id, payload).

        Closing the generator cancels the underlying RPC, which requeues
        any unacked deliveries server-side.
        """
        call = self._pull(topic.encode("utf-8"))
        try:
            for framed in call:
                delivery_b, _, payload = framed.partition(_TOPIC_SEP)
                yield delivery_b.decode("ascii"), payload
        finally:
            call.cancel()

    def ack(self, topic: str, delivery_id: str, ok: bool = True) -> None:
        self._ack(topic.encode("utf-8") + _TOPIC_SEP +
                  delivery_id.encode("ascii") + _TOPIC_SEP +
                  (b"ok" if ok else b"fail"))

    def close(self) -> None:
        self._channel.close()


def _wants_ack(handler: Callable) -> bool:
    """True if the handler accepts a second (ack) argument — manual-ack
    mode, used by consumers that finish work asynchronously (TPU worker).

    Inference requires two or more NAMED positional parameters; a bare
    ``*args`` handler is NOT treated as manual-ack (a generic ``lambda *a``
    would otherwise never ack and cycle every frame to dead-letter).  Pass
    ``manual_ack=True`` to ``subscribe`` to opt in explicitly."""
    try:
        sig = inspect.signature(handler)
    except (TypeError, ValueError):
        return False
    params = [p for p in sig.parameters.values()
              if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    return len(params) >= 2


class RemoteBus:
    """InMemoryBus-shaped facade over a GrpcBusClient for worker processes.

    `publish` is a Publish RPC to the host; `subscribe` starts a puller
    thread streaming the topic's queue and dispatching to local handlers
    (competing consumers: multiple workers pulling one topic split the
    stream — exactly the work-queue semantics of the reference's pubsub,
    `distributed/pubsub.go:149-254`).

    Delivery contract: a one-argument handler is retried inline up to
    `max_redeliveries` times; success acks the frame, final failure NACKs
    it so the SERVER requeues it for another worker (`pubsub.go:157-171`'s
    broker-redelivers semantics) — a failing handler no longer silently
    loses the work item.  A two-argument handler ``(payload, ack)`` owns
    the ack itself: call ``ack(True)`` when the work is durably done,
    ``ack(False)`` to requeue; a worker crash before acking requeues
    server-side via stream teardown or ack timeout.
    """

    def __init__(self, target: str = "127.0.0.1:50551",
                 max_redeliveries: int = 3):
        self._client = GrpcBusClient(target)
        self.max_redeliveries = max_redeliveries
        # Inline-redelivery policy (shared utils/resilience.py schedule):
        # base delay 0 preserves the historical immediate retries, but a
        # server-directed ``retry_after_s`` hint (FLOOD_WAIT taxonomy) is
        # honoured, capped to keep the pull thread responsive.
        self._retry = resilience.RetryPolicy(
            max_attempts=max_redeliveries + 1, base_delay_s=0.0,
            jitter=0.0, retry_after_cap_s=2.0)
        self._handlers: Dict[str, list] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()

    def publish(self, topic: str, payload: Any) -> None:
        self._client.publish(topic, payload)

    def subscribe(self, topic: str, handler: Callable[..., None],
                  manual_ack: Optional[bool] = None) -> None:
        """Register ``handler`` for ``topic``.

        ``manual_ack=None`` infers the mode from the signature (two named
        positional params → ``(payload, ack)``); pass True/False to force.
        A manual-ack handler OWNS its topic's deliveries, so mixing it with
        any other handler on the same topic is rejected at subscribe time
        rather than silently shadowing the others.
        """
        wants = _wants_ack(handler) if manual_ack is None else manual_ack
        with self._lock:
            existing = self._handlers.get(topic, [])
            if wants and existing:
                raise ValueError(
                    f"manual-ack handler on '{topic}' would shadow "
                    f"{len(existing)} existing subscriber(s); use a "
                    f"dedicated topic per manual-ack consumer")
            if existing and any(w for _, w in existing):
                raise ValueError(
                    f"topic '{topic}' already has a manual-ack handler; "
                    f"additional subscribers would never receive frames")
            self._handlers.setdefault(topic, []).append((handler, wants))
            if topic in self._threads:
                return
            t = threading.Thread(target=self._pull_loop, args=(topic,),
                                 daemon=True, name=f"dct-bus-pull-{topic}")
            self._threads[topic] = t
            t.start()

    def _pull_loop(self, topic: str) -> None:
        while not self._stop.is_set():
            try:
                for delivery_id, frame in self._client.pull(topic):
                    if self._stop.is_set():
                        return
                    self._dispatch(topic, delivery_id, frame)
            except grpc.RpcError as e:
                if self._stop.is_set():
                    return
                logger.warning("pull stream for %s dropped (%s); "
                               "reconnecting", topic, e.code()
                               if hasattr(e, "code") else e)
                self._stop.wait(1.0)

    def _safe_ack(self, topic: str, delivery_id: str, ok: bool) -> None:
        if self._stop.is_set():
            # Shutting down: the channel may already be closed.  The server
            # requeues the unacked delivery via stream teardown.
            return
        try:
            self._client.ack(topic, delivery_id, ok)
        except grpc.RpcError as e:
            # Server unreachable: it will requeue via stream teardown or
            # ack timeout anyway.
            logger.warning("ack for %s/%s failed: %s", topic, delivery_id, e)
        except ValueError:
            # grpc raises bare ValueError ("Cannot invoke RPC on closed
            # channel!") when close() won the race against a dispatching
            # pull thread; same requeue guarantee applies.
            logger.warning("ack for %s/%s skipped: channel closed",
                           topic, delivery_id)

    def _dispatch(self, topic: str, delivery_id: str, frame: bytes) -> None:
        try:
            payload = json.loads(frame.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            logger.error("dropping undecodable message on %s", topic)
            # Parity with the reference: unmarshal errors are never
            # retried (`pubsub.go:157-171`) — ack so it isn't redelivered.
            self._safe_ack(topic, delivery_id, True)
            return
        with self._lock:
            handlers = list(self._handlers.get(topic, []))
        manual = [h for h, wants in handlers if wants]
        if manual:
            # Manual-ack consumers own the delivery; one handler per topic
            # (the TPU worker pattern).
            handler = manual[0]
            acked = threading.Event()

            def ack(ok: bool = True) -> None:
                if not acked.is_set():
                    acked.set()
                    self._safe_ack(topic, delivery_id, ok)

            with trace.payload_span("bus.deliver", payload, topic=topic,
                                    transport="grpc", manual_ack=True):
                try:
                    handler(payload, ack)
                except Exception as e:
                    logger.warning("handler error on %s: %s", topic, e)
                    ack(False)
            return
        ok = True
        with trace.payload_span("bus.deliver", payload, topic=topic,
                                transport="grpc"):
            for handler, _ in handlers:
                try:
                    resilience.retry_call(
                        handler, payload, retry=self._retry,
                        op=f"bus.remote.{topic}", stop=self._stop)
                except Exception as e:
                    logger.error("handler exhausted redeliveries on %s: %s",
                                 topic, e)
                    ok = False
        # NACK on final failure: the server requeues (bumping its attempt
        # count) so another worker can take the item instead of it being
        # silently dropped.
        self._safe_ack(topic, delivery_id, ok)

    def start(self) -> None:
        return None  # threads start on subscribe

    def close(self) -> None:
        self._stop.set()
        self._client.close()
        for t in self._threads.values():
            t.join(timeout=2.0)
