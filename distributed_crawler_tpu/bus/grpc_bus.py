"""gRPC transport: the DCN leg of the message bus.

The reference talked to its sidecar over gRPC with 201 MB frames
(`state/daprstate.go:104-133`); here the bus itself is the service.  Uses
gRPC generic handlers with raw-bytes (de)serializers — no protoc codegen —
carrying the same JSON payloads as InMemoryBus plus codec frames for record
batches.  Three RPCs:

- Publish (unary): topic + payload -> ack
- Pull (server-streaming): workers pull frames for a topic, giving
  backpressure-aware feeding of the TPU worker
- Ack (unary): per-delivery acknowledgement closing the at-least-once loop

Delivery guarantees (parity with `distributed/pubsub.go:157-254`, which
relied on the broker redelivering on handler error): every pulled frame
carries a delivery ID and stays "in flight" on the server until acked.
Unacked frames are requeued when the pulling stream dies, when the client
nacks (handler exhausted its retries), or when the ack deadline passes —
so a worker crash mid-handler no longer loses work.  A frame redelivered
more than ``max_attempts`` times is dead-lettered, bounding poison-message
loops: with a spool configured it lands in the persisted dead-letter
queue (`bus/spool.py`; list/inspect/replay via ``tools/dlq.py`` or the
``/dlq`` endpoint), without one it is logged and dropped — either way
counted in ``bus_dead_letters_total{topic}`` and flight-recorded.

Broker durability (``spool_dir``): the reference's broker was a Redis
behind a Dapr sidecar — it survived its own restarts.  Passing
``spool_dir`` gives this server the same property: every pull-topic frame
is journaled in a per-topic WAL (enqueue/requeue/ack/dead events,
`bus/spool.py`), and a NEW server constructed over the same directory
rebuilds the queued + unacked-in-flight frame set — attempt counts and
frame ids preserved — so a broker crash redelivers instead of losing.
The publisher half of the outage story lives in `bus/outbox.py`.

Tensor traffic never rides this bus: on-slice collectives are XLA/ICI
(`parallel/`).  This is coordination + record streaming only.
"""

from __future__ import annotations

import inspect
import json
import logging
import queue
import threading
import time
import uuid
from concurrent import futures
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import grpc

from ..utils import flight, resilience, trace
from ..utils.metrics import REGISTRY, MetricsRegistry
from .outbox import DurableOutbox, OutboxConfig
from .payload import serialize_payload
from .spool import BusSpool

logger = logging.getLogger("dct.bus.grpc")

SERVICE_NAME = "dct.bus.Bus"
MAX_FRAME_BYTES = 201 * 1024 * 1024  # parity: daprstate.go:108-110

DEFAULT_ACK_TIMEOUT_S = 300.0
DEFAULT_MAX_ATTEMPTS = 5

_TOPIC_SEP = b"\x00"


def _encode_envelope(topic: str, payload: bytes) -> bytes:
    return topic.encode("utf-8") + _TOPIC_SEP + payload


def _decode_envelope(data: bytes) -> tuple:
    topic, _, payload = data.partition(_TOPIC_SEP)
    return topic.decode("utf-8"), payload


def _identity(b: bytes) -> bytes:
    return b


@dataclass
class _QueuedFrame:
    payload: bytes
    attempts: int = 0
    # Stable spool frame id (minted at enqueue, kept across requeues AND
    # broker generations); "" when the server runs without a spool.
    fid: str = ""


@dataclass
class _Inflight:
    payload: bytes
    attempts: int
    deadline: float
    stream_id: int
    fid: str = ""


@dataclass
class _TopicQueue:
    """Pull queue + in-flight ledger for one topic."""

    q: "queue.Queue[_QueuedFrame]" = field(default_factory=queue.Queue)
    inflight: Dict[str, _Inflight] = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock)


class GrpcBusServer:
    """Hosts topics; local subscribers receive published payloads, and remote
    pullers stream queued record batches with per-delivery acks."""

    def __init__(self, address: str = "127.0.0.1:50551", max_workers: int = 8,
                 ack_timeout_s: float = DEFAULT_ACK_TIMEOUT_S,
                 max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 spool_dir: Optional[str] = None,
                 registry: MetricsRegistry = REGISTRY):
        self.address = address
        self.ack_timeout_s = ack_timeout_s
        self.max_attempts = max_attempts
        # Durability (bus/spool.py): with a spool dir every pull-topic
        # frame is WAL-journaled and dead letters persist; without one
        # the server keeps the historical RAM-only behavior.
        self._spool = BusSpool(spool_dir) if spool_dir else None
        self._killed = False
        self.m_dead = registry.counter(
            "bus_dead_letters_total",
            "frames dead-lettered per topic (exhausted max_attempts or a "
            "local handler's retry budget)")
        self.m_redeliveries = registry.counter(
            "bus_redeliveries_total",
            "frames requeued for redelivery per topic (nack, ack timeout, "
            "or pull-stream death)")
        self.m_unrouted = registry.counter(
            "bus_dropped_no_route_total",
            "publishes that reached a topic with no handler and no pull "
            "queue (held in the DLQ spool when durability is on, dropped "
            "otherwise)")
        # WARN once per topic (then debug): a fan-out topic nobody
        # subscribed must be visible, not a per-frame log storm.
        self._unrouted_warned: set = set()
        # Unrouted frames held in the DLQ are capped per topic: a
        # high-volume announce stream with no consumer must not grow the
        # spool without bound (the counter keeps the true total).
        self._unrouted_spooled: Dict[str, int] = {}
        self.unrouted_spool_cap = 1024
        # Local-handler delivery policy: the backoff/attempt schedule is
        # declared ONCE (utils/resilience.py) instead of hand-rolled per
        # loop; a handler raising a FLOOD_WAIT-style error (carrying
        # ``retry_after_s``) gets its server-directed backoff honoured,
        # capped so one hostile hint can't park a topic's dispatch thread.
        self._local_retry = resilience.RetryPolicy(
            max_attempts=max_attempts, base_delay_s=0.05, max_delay_s=0.5,
            jitter=0.0, retry_after_cap_s=2.0)
        self._handlers: Dict[str, list] = {}
        self._pull_queues: Dict[str, _TopicQueue] = {}
        self._lock = threading.RLock()
        self._stream_counter = 0
        self.dead_letters = 0
        # Local-subscriber dispatch: per-topic queue + worker thread, so
        # handlers run OFF the gRPC thread and get the same bounded-retry
        # treatment as pulled frames (`distributed/pubsub.go:157-171`
        # retried every subscriber on error; inline-and-swallow was
        # at-most-once).
        self._local_queues: Dict[str, "queue.Queue"] = {}
        self._local_threads: Dict[str, threading.Thread] = {}
        self._local_idle = threading.Condition()
        self._local_inflight = 0
        self._stop = threading.Event()
        self._sweeper: Optional[threading.Thread] = None
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.max_receive_message_length", MAX_FRAME_BYTES),
                     ("grpc.max_send_message_length", MAX_FRAME_BYTES)])
        handlers = {
            "Publish": grpc.unary_unary_rpc_method_handler(
                self._publish_rpc, request_deserializer=_identity,
                response_serializer=_identity),
            "Pull": grpc.unary_stream_rpc_method_handler(
                self._pull_rpc, request_deserializer=_identity,
                response_serializer=_identity),
            "Ack": grpc.unary_unary_rpc_method_handler(
                self._ack_rpc, request_deserializer=_identity,
                response_serializer=_identity),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))
        self.bound_port = self._server.add_insecure_port(address)
        if self._spool is not None:
            self._rebuild_from_spool()

    def _rebuild_from_spool(self) -> None:
        """Resume path: rebuild every spooled topic's queue (queued AND
        unacked-in-flight frames of the dead generation, attempt counts
        preserved) before the first RPC can land."""
        # The per-topic unrouted-hold cap counts what is already ON DISK,
        # not just this generation's appends — a supervisor restart loop
        # must not grow the DLQ by another cap's worth per generation.
        for topic in self._spool.dlq.topics():
            held = sum(1 for e in self._spool.dlq.entries(topic)
                       if e.reason == "no_route" and not e.replayed)
            if held:
                self._unrouted_spooled[topic] = held
        restored: Dict[str, int] = {}
        for topic in self._spool.existing_topics():
            tq = self._ensure_topic_queue(topic)
            restored[topic] = tq.q.qsize()
        if restored:
            flight.record("bus_resume", address=self.address,
                          restored=restored,
                          frames=sum(restored.values()))
            logger.info("bus spool resume: %d frame(s) restored across "
                        "%d topic(s): %s", sum(restored.values()),
                        len(restored), restored)

    def _ensure_topic_queue(self, topic: str) -> _TopicQueue:
        """Create a pull queue on first use; with a spool, the topic's
        live WAL frames are replayed into it exactly once."""
        with self._lock:
            tq = self._pull_queues.get(topic)
            if tq is not None:
                return tq
            tq = _TopicQueue()
            if self._spool is not None:
                for frame in self._spool.replay(topic):
                    tq.q.put(_QueuedFrame(frame.payload, frame.attempts,
                                          frame.fid))
            self._pull_queues[topic] = tq
            return tq

    # --- service ----------------------------------------------------------
    def _publish_rpc(self, request: bytes, context) -> bytes:
        if self._killed:
            raise RuntimeError("bus server killed")
        topic, payload = _decode_envelope(request)
        with self._lock:
            has_handlers = bool(self._handlers.get(topic))
            tq = self._pull_queues.get(topic)
            lq = self._local_queues.get(topic) if has_handlers else None
        if tq is None and lq is None:
            # No handler, no pull queue: this used to ack b"ok" and
            # silently drop the frame.  Always count (+ WARN once per
            # topic); with durability on, hold it in the dead-letter
            # spool (reason ``no_route``, capped per topic) so an
            # operator can `tools/dlq.py --replay` it once a consumer
            # exists instead of losing it forever.
            self._record_unrouted(topic, payload)
        if tq is not None:
            fid = ""
            if self._spool is not None:
                # WAL append BEFORE the in-memory enqueue: a crash
                # between the two redelivers on restart instead of
                # acking a frame that never survived.
                fid = self._spool.enqueue(topic, payload)
            tq.q.put(_QueuedFrame(payload, 0, fid))
        if lq is not None:
            try:
                decoded = json.loads(payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                # Undecodable payloads are dropped, never retried.
                logger.error("dropping undecodable message on %s", topic)
                return b"ok"
            with self._local_idle:
                self._local_inflight += 1
            lq.put(decoded)
        return b"ok"

    def _local_dispatch_loop(self, topic: str, lq: "queue.Queue") -> None:
        # Keeps draining after _stop until the queue is empty: a Publish we
        # answered b"ok" to must reach local handlers even across close()
        # (retry backoffs short-circuit once _stop is set).
        while True:
            if self._killed:
                return  # kill(): RAM state is gone, nothing drains
            try:
                decoded = lq.get(timeout=0.25)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                with self._lock:
                    handlers = list(self._handlers.get(topic, []))
                with trace.payload_span("bus.deliver", decoded, topic=topic,
                                        transport="grpc-local"):
                    for handler in handlers:
                        try:
                            # Stop-event-aware waits: close() never blocks
                            # on a backoff mid-drain.
                            resilience.retry_call(
                                handler, decoded, retry=self._local_retry,
                                op=f"bus.local.{topic}", stop=self._stop)
                        except Exception as e:
                            self._dead_letter(
                                topic, "",
                                json.dumps(decoded,
                                           default=str).encode("utf-8"),
                                self.max_attempts,
                                reason=f"local_handler: {e}")
            finally:
                with self._local_idle:
                    self._local_inflight -= 1
                    if self._local_inflight == 0:
                        self._local_idle.notify_all()

    def flush_local(self, timeout_s: float = 5.0) -> bool:
        """Block until every queued local delivery has been dispatched
        (tests / orderly shutdown).  Returns False on timeout."""
        with self._local_idle:
            return self._local_idle.wait_for(
                lambda: self._local_inflight == 0, timeout=timeout_s)

    def _sweep_loop(self) -> None:
        # Dedicated sweeper: ack deadlines fire even with no active puller
        # (a blocked or absent consumer must not pin frames in flight).
        interval = max(0.05, min(1.0, self.ack_timeout_s / 4.0))
        while not self._stop.wait(interval):
            with self._lock:
                topics = list(self._pull_queues.items())
            for topic, tq in topics:
                self._sweep_expired(topic, tq)

    def _spool_op(self, fn, *args) -> None:
        """Run a spool mutation, tolerating a spool closed by kill():
        a requeue/ack racing the chaos kill simply doesn't commit — the
        frame stays journaled in its pre-race state and the next
        generation redelivers it, exactly like a real SIGKILL landing
        mid-write.  Any other spool failure still raises."""
        try:
            fn(*args)
        except RuntimeError:
            if not self._killed:
                raise
            logger.debug("spool op skipped: broker killed mid-%s",
                         getattr(fn, "__name__", "op"))

    def _record_unrouted(self, topic: str, payload: bytes) -> None:
        self.m_unrouted.labels(topic=topic).inc()
        spooled = False
        if self._spool is not None:
            with self._lock:
                n = self._unrouted_spooled.get(topic, 0)
                spooled = n < self.unrouted_spool_cap
                if spooled:
                    self._unrouted_spooled[topic] = n + 1
            if spooled:
                from .spool import new_frame_id

                self._spool.dlq.append(topic, new_frame_id(), payload,
                                       attempts=0, reason="no_route")
        flight.record("bus_unrouted", topic=topic, spooled=spooled)
        first = topic not in self._unrouted_warned
        self._unrouted_warned.add(topic)
        log = logger.warning if first else logger.debug
        log("no route for message on %s (no handler, no pull queue); %s",
            topic,
            "held in the DLQ spool" if spooled else
            ("DLQ spool cap reached; frame dropped" if self._spool
             is not None else "frame DROPPED (no spool configured)"))

    def _dead_letter(self, topic: str, fid: str, payload: bytes,
                     attempts: int, reason: str) -> None:
        """A frame leaves the delivery loop for good: counted (the
        ``dead_letters`` int is kept for back-compat; += on an int is not
        atomic, hence the lock), flight-recorded, and — with a spool —
        persisted to the per-topic dead-letter queue instead of dropped
        (``tools/dlq.py`` replays it)."""
        with self._lock:
            self.dead_letters += 1
        self.m_dead.labels(topic=topic).inc()
        persisted = self._spool is not None and not self._killed
        if persisted:
            fid = self._spool.dead(topic, fid, payload, attempts, reason)
        flight.record("dead_letter", topic=topic, frame=fid,
                      attempts=attempts, reason=reason,
                      persisted=persisted)
        logger.error(
            "dead-lettering frame on %s after %d attempts (id=%s; %s): %s",
            topic, attempts, fid or "-",
            "persisted to DLQ spool" if persisted else "DROPPED", reason)

    def _requeue_or_drop(self, topic: str, tq: _TopicQueue,
                         delivery_id: str, inf: _Inflight) -> None:
        """inf has been removed from the inflight map by the caller."""
        if inf.attempts + 1 >= self.max_attempts:
            self._dead_letter(topic, inf.fid, inf.payload,
                              inf.attempts + 1, reason="max_attempts")
            return
        attempts = inf.attempts + 1
        self.m_redeliveries.labels(topic=topic).inc()
        if self._spool is not None:
            self._spool_op(self._spool.requeue, topic, inf.fid, attempts)
        tq.q.put(_QueuedFrame(inf.payload, attempts=attempts, fid=inf.fid))

    def _sweep_expired(self, topic: str, tq: _TopicQueue) -> None:
        now = time.monotonic()
        with tq.lock:
            expired = [(d, i) for d, i in tq.inflight.items()
                       if i.deadline <= now]
            for d, _ in expired:
                del tq.inflight[d]
        for d, inf in expired:
            logger.warning("ack timeout on %s (id=%s); requeueing", topic, d)
            self._requeue_or_drop(topic, tq, d, inf)

    def _pull_rpc(self, request: bytes, context) -> Iterator[bytes]:
        topic = request.decode("utf-8")
        tq = self._ensure_topic_queue(topic)
        with self._lock:
            self._stream_counter += 1
            stream_id = self._stream_counter
        try:
            while context.is_active():
                self._sweep_expired(topic, tq)
                # Pop and register in-flight ATOMICALLY under tq.lock: a
                # frame popped but not yet registered would be invisible
                # to pending_count(), letting drain() declare the broker
                # empty while a frame is mid-handoff.
                with tq.lock:
                    try:
                        frame = tq.q.get_nowait()
                    except queue.Empty:
                        frame = None
                    else:
                        delivery_id = uuid.uuid4().hex
                        tq.inflight[delivery_id] = _Inflight(
                            frame.payload, frame.attempts,
                            time.monotonic() + self.ack_timeout_s,
                            stream_id, frame.fid)
                if frame is None:
                    time.sleep(0.05)
                    continue
                try:
                    yield delivery_id.encode("ascii") + _TOPIC_SEP + \
                        frame.payload
                except BaseException:
                    # Stream cancelled between pop and consume: requeue so
                    # the batch isn't lost (at-least-once for pulled frames).
                    with tq.lock:
                        inf = tq.inflight.pop(delivery_id, None)
                    if inf is not None:
                        tq.q.put(_QueuedFrame(inf.payload, inf.attempts,
                                              inf.fid))
                    raise
        finally:
            # Stream gone (worker died / disconnected): everything this
            # stream delivered but never acked goes back on the queue.
            with tq.lock:
                orphaned = [(d, i) for d, i in tq.inflight.items()
                            if i.stream_id == stream_id]
                for d, _ in orphaned:
                    del tq.inflight[d]
            for d, inf in orphaned:
                logger.info("stream for %s closed with unacked frame "
                            "(id=%s); requeueing", topic, d)
                self._requeue_or_drop(topic, tq, d, inf)

    def _ack_rpc(self, request: bytes, context) -> bytes:
        topic_b, _, rest = request.partition(_TOPIC_SEP)
        delivery_b, _, status = rest.partition(_TOPIC_SEP)
        topic = topic_b.decode("utf-8")
        delivery_id = delivery_b.decode("ascii")
        with self._lock:
            tq = self._pull_queues.get(topic)
        if tq is None:
            return b"unknown-topic"
        with tq.lock:
            inf = tq.inflight.pop(delivery_id, None)
        if inf is None:
            return b"unknown-delivery"  # already requeued/expired
        if status != b"ok":
            self._requeue_or_drop(topic, tq, delivery_id, inf)
        elif self._spool is not None:
            # Durably done: the WAL forgets the frame (and compacts once
            # the acked prefix dominates).
            self._spool_op(self._spool.ack, topic, inf.fid)
        return b"ok"

    # --- local wiring -----------------------------------------------------
    def subscribe(self, topic: str, handler: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)
            if topic not in self._local_queues:
                lq: "queue.Queue" = queue.Queue()
                self._local_queues[topic] = lq
                t = threading.Thread(
                    target=self._local_dispatch_loop, args=(topic, lq),
                    daemon=True, name=f"dct-bus-local-{topic}")
                self._local_threads[topic] = t
                t.start()

    def publish(self, topic: str, payload: Any) -> None:
        """Local publish: same fan-out as a remote Publish RPC, so the host
        process (e.g. the orchestrator) can use the server as its bus.
        Raises once the server is killed — a durable publisher (the
        `bus/outbox.py` outbox) buffers and retries against the next
        generation."""
        payload = trace.inject(payload)
        self._publish_rpc(_encode_envelope(topic, serialize_payload(payload)),
                          None)

    def enable_pull(self, topic: str) -> None:
        self._ensure_topic_queue(topic)

    def pending_count(self, topic: str) -> int:
        """Queued + in-flight frames (observability / tests)."""
        with self._lock:
            tq = self._pull_queues.get(topic)
        if tq is None:
            return 0
        with tq.lock:
            return tq.q.qsize() + len(tq.inflight)

    def drain(self, timeout_s: float = 30.0,
              poll_s: float = 0.2) -> bool:
        """Block until every pull topic is empty (queued AND in-flight),
        or the timeout expires; returns True when fully drained.

        A broker-hosting process that exits the moment ITS work is done
        (the orchestrator after crawl completion) takes every undelivered
        frame down with it — consumers that were still warming up lose
        their batches.  Call this before close().
        """
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                topics = list(self._pull_queues)
            remaining = {t: self.pending_count(t) for t in topics}
            remaining = {t: n for t, n in remaining.items() if n}
            if not remaining:
                return True
            if time.monotonic() >= deadline:
                logger.warning(
                    "bus drain timed out with frames pending: %s", remaining)
                return False
            time.sleep(poll_s)

    def dlq_snapshot(self, topic: Optional[str] = None,
                     id: Optional[str] = None) -> Dict[str, Any]:
        """The ``/dlq`` endpoint body: per-topic dead-letter counts +
        newest entry metadata (full payload only for an explicit ``id``
        lookup).  Works — empty — without a spool, so the endpoint never
        404s on a durability-off broker."""
        if self._spool is None:
            return {"enabled": False, "topics": {},
                    "dead_letters_total": self.dead_letters}
        body = self._spool.dlq.snapshot(topic=topic or None, fid=id or None)
        body["enabled"] = True
        body["dead_letters_total"] = self.dead_letters
        return body

    def dlq_replay(self, topic: str, fid: str) -> Dict[str, Any]:
        """Re-drive one dead letter onto its topic (the ``tools/dlq.py``
        replay verb): the frame re-enters the normal delivery loop with a
        fresh attempt budget, and the DLQ entry is marked replayed."""
        if self._spool is None:
            raise RuntimeError("dead-letter replay needs a spool_dir")
        entry = self._spool.dlq.get(topic, fid)
        if entry is None:
            raise KeyError(f"no dead letter {fid!r} on topic {topic!r}")
        if entry.reason == "no_route":
            # Release the hold's cap slot BEFORE re-publishing: if the
            # topic is STILL unrouted, the replayed frame re-enters the
            # hold path and must fit inside the cap, not be dropped.
            with self._lock:
                if self._unrouted_spooled.get(topic, 0) > 0:
                    self._unrouted_spooled[topic] -= 1
        self._publish_rpc(_encode_envelope(topic, entry.payload), None)
        self._spool.dlq.mark_replayed(topic, fid)
        flight.record("dlq_replay", topic=topic, frame=fid)
        return entry.meta()

    def start(self) -> None:
        self._server.start()
        self._sweeper = threading.Thread(target=self._sweep_loop,
                                         daemon=True, name="dct-bus-sweeper")
        self._sweeper.start()
        logger.info("bus server listening on %s", self.address)

    def kill(self) -> None:
        """Abrupt-death chaos seam (the `loadgen` bus target): hard-stop
        the gRPC server and drop ALL in-RAM state — queued frames,
        in-flight ledgers, local dispatch queues — exactly like a
        SIGKILLed broker process.  No drain, no local flush, no WAL
        compaction; what survives is what the spool already journaled.
        A new `GrpcBusServer` over the same ``spool_dir`` is the restart.
        """
        if self._killed:
            return
        self._killed = True
        pending = {t: self.pending_count(t)
                   for t in list(self._pull_queues)}
        flight.record("bus_kill", address=self.address,
                      pending={t: n for t, n in pending.items() if n})
        logger.warning("bus server KILLED (chaos) with pending frames: %s",
                       {t: n for t, n in pending.items() if n} or "none")
        self._server.stop(None)   # immediate: in-flight RPCs are aborted
        self._stop.set()
        if self._spool is not None:
            # Late appends from a racing publish must fail loudly (the
            # publisher's outbox retries against the next generation)
            # rather than land in a WAL the new generation already read.
            self._spool.close(compact=False)
        if self._sweeper is not None:
            self._sweeper.join(timeout=2.0)
        for t in self._local_threads.values():
            t.join(timeout=2.0)

    def close(self, grace: float = 0.5) -> None:
        if self._killed:
            # Already hard-stopped; there is nothing left to drain.
            for t in self._local_threads.values():
                t.join(timeout=1.0)
            return
        # stop() returns immediately; in-flight Publish RPCs keep running
        # for up to `grace`.  Wait for full termination BEFORE setting
        # _stop, or a dispatch thread could exit on an empty queue while an
        # in-flight RPC is about to enqueue a frame we already acked b"ok".
        self._server.stop(grace).wait(grace + 5.0)
        self._stop.set()          # dispatch loops drain, then exit
        if not self.flush_local(timeout_s=max(grace, 5.0)):
            with self._local_idle:
                remaining = self._local_inflight
            logger.error("bus closed with %d undelivered local "
                         "message(s)", remaining)
        if self._sweeper is not None:
            self._sweeper.join(timeout=2.0)
        for t in self._local_threads.values():
            t.join(timeout=2.0)
        if self._spool is not None:
            self._spool.close(compact=True)


class GrpcBusClient:
    """Publishes payloads / pulls record-batch frames from a GrpcBusServer.

    **Wedged-channel self-healing**: a channel hammered with RPCs while
    its broker is down can end up permanently stuck in this grpcio's
    connect machinery ("Failed to connect to remote host: Timeout
    occurred: FD Shutdown" forever, even once a new broker process is
    listening on the same address — reproduced live driving a killed
    partitioned-bus shard; ~50 failed publishes over a 12 s outage were
    enough).  The app-level retry/outbox layers fail fast against the
    wedged channel without ever re-dialing, so the client itself now
    counts consecutive unary transport failures and REBUILDS the
    channel (rate-limited) once they cross a threshold — a fresh
    channel dials a restarted broker within its capped backoff instead
    of trusting wedged subchannel state.
    """

    # Rebuild after this many consecutive unary RPC failures, at most
    # once per cooldown window (an outage longer than the window just
    # pays one cheap channel rebuild per window).
    REBUILD_AFTER_FAILURES = 8
    REBUILD_COOLDOWN_S = 2.0

    def __init__(self, target: str = "127.0.0.1:50551"):
        self.target = target
        self._state_lock = threading.Lock()
        self._consecutive_failures = 0
        self._last_rebuild = 0.0
        self.rebuilds = 0
        self._build_channel()

    def _build_channel(self) -> None:
        self._channel = grpc.insecure_channel(
            self.target,
            options=[("grpc.max_receive_message_length", MAX_FRAME_BYTES),
                     ("grpc.max_send_message_length", MAX_FRAME_BYTES),
                     # Cap the CHANNEL's own reconnect backoff: grpc core
                     # grows it toward 2 minutes after a few failed
                     # dials, so a broker that restarts after a ~5 s
                     # outage could sit unreachable for ANOTHER minute+
                     # while the app-level retry/outbox machinery
                     # (which fails fast from the backoff state without
                     # re-dialing) believes it is retrying.
                     ("grpc.min_reconnect_backoff_ms", 200),
                     ("grpc.max_reconnect_backoff_ms", 5000)])
        self._publish = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Publish", request_serializer=_identity,
            response_deserializer=_identity)
        self._pull = self._channel.unary_stream(
            f"/{SERVICE_NAME}/Pull", request_serializer=_identity,
            response_deserializer=_identity)
        self._ack = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Ack", request_serializer=_identity,
            response_deserializer=_identity)

    def _note_ok(self) -> None:
        with self._state_lock:
            self._consecutive_failures = 0

    def _note_failure(self) -> None:
        rebuild = False
        with self._state_lock:
            self._consecutive_failures += 1
            now = time.monotonic()
            if self._consecutive_failures >= self.REBUILD_AFTER_FAILURES \
                    and now - self._last_rebuild >= self.REBUILD_COOLDOWN_S:
                self._last_rebuild = now
                self._consecutive_failures = 0
                self.rebuilds += 1
                old, rebuild = self._channel, True
                self._build_channel()
        if rebuild:
            logger.warning(
                "bus channel to %s rebuilt after sustained transport "
                "failure (rebuild #%d); live pull streams on the old "
                "channel will redial onto the new one", self.target,
                self.rebuilds)
            try:
                old.close()
            except Exception as e:  # noqa: BLE001 — best-effort close
                logger.debug("old channel close failed: %s", e)

    def _unary(self, stub_name: str, request: bytes) -> bytes:
        stub = getattr(self, stub_name)
        try:
            response = stub(request)
        except grpc.RpcError:
            self._note_failure()
            raise
        self._note_ok()
        return response

    def publish(self, topic: str, payload: Any) -> None:
        # Same propagation seam as InMemoryBus.publish: the envelope
        # crosses a process boundary here, which is exactly the hop the
        # parent_span stamp exists for.
        payload = trace.inject(payload)
        self._unary("_publish",
                    _encode_envelope(topic, serialize_payload(payload)))

    def publish_frame(self, topic: str, frame: bytes) -> None:
        """Publish an already-encoded codec frame (record batches)."""
        self._unary("_publish", _encode_envelope(topic, frame))

    def pull(self, topic: str) -> Iterator[Tuple[str, bytes]]:
        """Server-streaming pull; yields (delivery_id, payload).

        Closing the generator cancels the underlying RPC, which requeues
        any unacked deliveries server-side.
        """
        call = self._pull(topic.encode("utf-8"))
        try:
            for framed in call:
                delivery_b, _, payload = framed.partition(_TOPIC_SEP)
                yield delivery_b.decode("ascii"), payload
        finally:
            call.cancel()

    def ack(self, topic: str, delivery_id: str, ok: bool = True) -> None:
        self._unary("_ack", topic.encode("utf-8") + _TOPIC_SEP +
                    delivery_id.encode("ascii") + _TOPIC_SEP +
                    (b"ok" if ok else b"fail"))

    def close(self) -> None:
        self._channel.close()


def _wants_ack(handler: Callable) -> bool:
    """True if the handler accepts a second (ack) argument — manual-ack
    mode, used by consumers that finish work asynchronously (TPU worker).

    Inference requires two or more NAMED positional parameters; a bare
    ``*args`` handler is NOT treated as manual-ack (a generic ``lambda *a``
    would otherwise never ack and cycle every frame to dead-letter).  Pass
    ``manual_ack=True`` to ``subscribe`` to opt in explicitly."""
    try:
        sig = inspect.signature(handler)
    except (TypeError, ValueError):
        return False
    params = [p for p in sig.parameters.values()
              if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    return len(params) >= 2


class RemoteBus:
    """InMemoryBus-shaped facade over a GrpcBusClient for worker processes.

    `publish` is a Publish RPC to the host; `subscribe` starts a puller
    thread streaming the topic's queue and dispatching to local handlers
    (competing consumers: multiple workers pulling one topic split the
    stream — exactly the work-queue semantics of the reference's pubsub,
    `distributed/pubsub.go:149-254`).

    Delivery contract: a one-argument handler is retried inline up to
    `max_redeliveries` times; success acks the frame, final failure NACKs
    it so the SERVER requeues it for another worker (`pubsub.go:157-171`'s
    broker-redelivers semantics) — a failing handler no longer silently
    loses the work item.  A two-argument handler ``(payload, ack)`` owns
    the ack itself: call ``ack(True)`` when the work is durably done,
    ``ack(False)`` to requeue; a worker crash before acking requeues
    server-side via stream teardown or ack timeout.
    """

    def __init__(self, target: str = "127.0.0.1:50551",
                 max_redeliveries: int = 3,
                 outbox: Optional[OutboxConfig] = None,
                 registry: MetricsRegistry = REGISTRY):
        self._client = GrpcBusClient(target)
        self.max_redeliveries = max_redeliveries
        # Inline-redelivery policy (shared utils/resilience.py schedule):
        # base delay 0 preserves the historical immediate retries, but a
        # server-directed ``retry_after_s`` hint (FLOOD_WAIT taxonomy) is
        # honoured, capped to keep the pull thread responsive.
        self._retry = resilience.RetryPolicy(
            max_attempts=max_redeliveries + 1, base_delay_s=0.0,
            jitter=0.0, retry_after_cap_s=2.0)
        # Reconnect schedule for a dropped pull stream: jittered
        # exponential backoff that RESETS on a successful pull, so a
        # restarting broker under a full fleet sees staggered redials
        # instead of the old synchronized 1 Hz stampede.
        self._reconnect = resilience.RetryPolicy(
            max_attempts=1 << 30, base_delay_s=0.1, max_delay_s=2.0,
            multiplier=2.0, jitter=0.25)
        # Durable publisher outbox (bus/outbox.py): with a config, every
        # publish is buffered-and-retried through the resilience layer
        # instead of raising a broker outage into the serving path.
        self.outbox: Optional[DurableOutbox] = None
        if outbox is not None:
            self.outbox = DurableOutbox(self._client.publish, outbox,
                                        registry=registry)
        self._handlers: Dict[str, list] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()

    def publish(self, topic: str, payload: Any) -> None:
        if self.outbox is not None:
            self.outbox.publish(topic, payload)
            return
        self._client.publish(topic, payload)

    def subscribe(self, topic: str, handler: Callable[..., None],
                  manual_ack: Optional[bool] = None) -> None:
        """Register ``handler`` for ``topic``.

        ``manual_ack=None`` infers the mode from the signature (two named
        positional params → ``(payload, ack)``); pass True/False to force.
        A manual-ack handler OWNS its topic's deliveries, so mixing it with
        any other handler on the same topic is rejected at subscribe time
        rather than silently shadowing the others.
        """
        wants = _wants_ack(handler) if manual_ack is None else manual_ack
        with self._lock:
            existing = self._handlers.get(topic, [])
            if wants and existing:
                raise ValueError(
                    f"manual-ack handler on '{topic}' would shadow "
                    f"{len(existing)} existing subscriber(s); use a "
                    f"dedicated topic per manual-ack consumer")
            if existing and any(w for _, w in existing):
                raise ValueError(
                    f"topic '{topic}' already has a manual-ack handler; "
                    f"additional subscribers would never receive frames")
            self._handlers.setdefault(topic, []).append((handler, wants))
            if topic in self._threads:
                return
            t = threading.Thread(target=self._pull_loop, args=(topic,),
                                 daemon=True, name=f"dct-bus-pull-{topic}")
            self._threads[topic] = t
            t.start()

    def _pull_loop(self, topic: str) -> None:
        attempt = 0
        while not self._stop.is_set():
            try:
                for delivery_id, frame in self._client.pull(topic):
                    if self._stop.is_set():
                        return
                    attempt = 0  # a delivered frame proves the broker is up
                    self._dispatch(topic, delivery_id, frame)
            except grpc.RpcError as e:
                if self._stop.is_set():
                    return
                delay = self._reconnect.delay_s(attempt)
                attempt = min(attempt + 1, 16)  # cap the exponent, not the
                # retries: the schedule plateaus at max_delay_s forever
                logger.warning("pull stream for %s dropped (%s); "
                               "reconnecting in %.2fs", topic,
                               e.code() if hasattr(e, "code") else e, delay)
                self._stop.wait(delay)

    def _safe_ack(self, topic: str, delivery_id: str, ok: bool) -> None:
        if self._stop.is_set():
            # Shutting down: the channel may already be closed.  The server
            # requeues the unacked delivery via stream teardown.
            return
        try:
            self._client.ack(topic, delivery_id, ok)
        except grpc.RpcError as e:
            # Server unreachable: it will requeue via stream teardown or
            # ack timeout anyway.
            logger.warning("ack for %s/%s failed: %s", topic, delivery_id, e)
        except ValueError:
            # grpc raises bare ValueError ("Cannot invoke RPC on closed
            # channel!") when close() won the race against a dispatching
            # pull thread; same requeue guarantee applies.
            logger.warning("ack for %s/%s skipped: channel closed",
                           topic, delivery_id)

    def _dispatch(self, topic: str, delivery_id: str, frame: bytes) -> None:
        try:
            payload = json.loads(frame.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            logger.error("dropping undecodable message on %s", topic)
            # Parity with the reference: unmarshal errors are never
            # retried (`pubsub.go:157-171`) — ack so it isn't redelivered.
            self._safe_ack(topic, delivery_id, True)
            return
        with self._lock:
            handlers = list(self._handlers.get(topic, []))
        manual = [h for h, wants in handlers if wants]
        if manual:
            # Manual-ack consumers own the delivery; one handler per topic
            # (the TPU worker pattern).
            handler = manual[0]
            acked = threading.Event()

            def ack(ok: bool = True) -> None:
                if not acked.is_set():
                    acked.set()
                    self._safe_ack(topic, delivery_id, ok)

            with trace.payload_span("bus.deliver", payload, topic=topic,
                                    transport="grpc", manual_ack=True):
                try:
                    handler(payload, ack)
                except Exception as e:
                    logger.warning("handler error on %s: %s", topic, e)
                    ack(False)
            return
        ok = True
        with trace.payload_span("bus.deliver", payload, topic=topic,
                                transport="grpc"):
            for handler, _ in handlers:
                try:
                    resilience.retry_call(
                        handler, payload, retry=self._retry,
                        op=f"bus.remote.{topic}", stop=self._stop)
                except Exception as e:
                    logger.error("handler exhausted redeliveries on %s: %s",
                                 topic, e)
                    ok = False
        # NACK on final failure: the server requeues (bumping its attempt
        # count) so another worker can take the item instead of it being
        # silently dropped.
        self._safe_ack(topic, delivery_id, ok)

    def start(self) -> None:
        return None  # threads start on subscribe

    def close(self) -> None:
        self._stop.set()
        if self.outbox is not None:
            # Give buffered publishes a brief chance to land; what
            # doesn't make it stays in the outbox WAL (when configured).
            self.outbox.close(drain_s=2.0)
        self._client.close()
        for t in self._threads.values():
            t.join(timeout=2.0)
