"""gRPC transport: the DCN leg of the message bus.

The reference talked to its sidecar over gRPC with 201 MB frames
(`state/daprstate.go:104-133`); here the bus itself is the service.  Uses
gRPC generic handlers with raw-bytes (de)serializers — no protoc codegen —
carrying the same JSON payloads as InMemoryBus plus codec frames for record
batches.  Two RPCs:

- Publish (unary): topic + payload -> ack
- StreamBatches (server-streaming pull): workers pull record-batch frames for
  a topic, giving backpressure-aware feeding of the TPU worker.

Tensor traffic never rides this bus: on-slice collectives are XLA/ICI
(`parallel/`).  This is coordination + record streaming only.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
from concurrent import futures
from typing import Any, Callable, Dict, Iterator, Optional

import grpc

from .payload import serialize_payload

logger = logging.getLogger("dct.bus.grpc")

SERVICE_NAME = "dct.bus.Bus"
MAX_FRAME_BYTES = 201 * 1024 * 1024  # parity: daprstate.go:108-110

_TOPIC_SEP = b"\x00"


def _encode_envelope(topic: str, payload: bytes) -> bytes:
    return topic.encode("utf-8") + _TOPIC_SEP + payload


def _decode_envelope(data: bytes) -> tuple:
    topic, _, payload = data.partition(_TOPIC_SEP)
    return topic.decode("utf-8"), payload


def _identity(b: bytes) -> bytes:
    return b


class GrpcBusServer:
    """Hosts topics; local subscribers receive published payloads, and remote
    pullers stream queued record batches."""

    def __init__(self, address: str = "127.0.0.1:50551", max_workers: int = 8):
        self.address = address
        self._handlers: Dict[str, list] = {}
        self._pull_queues: Dict[str, "queue.Queue[bytes]"] = {}
        self._lock = threading.RLock()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[("grpc.max_receive_message_length", MAX_FRAME_BYTES),
                     ("grpc.max_send_message_length", MAX_FRAME_BYTES)])
        handlers = {
            "Publish": grpc.unary_unary_rpc_method_handler(
                self._publish_rpc, request_deserializer=_identity,
                response_serializer=_identity),
            "Pull": grpc.unary_stream_rpc_method_handler(
                self._pull_rpc, request_deserializer=_identity,
                response_serializer=_identity),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))
        self.bound_port = self._server.add_insecure_port(address)

    # --- service ----------------------------------------------------------
    def _publish_rpc(self, request: bytes, context) -> bytes:
        topic, payload = _decode_envelope(request)
        with self._lock:
            handlers = list(self._handlers.get(topic, []))
            q = self._pull_queues.get(topic)
        if q is not None:
            q.put(payload)
        if handlers:
            try:
                decoded = json.loads(payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                # Undecodable payloads are dropped, never retried.
                logger.error("dropping undecodable message on %s", topic)
                return b"ok"
            for handler in handlers:
                try:
                    handler(decoded)
                except Exception as e:
                    logger.warning("handler error on %s: %s", topic, e)
        return b"ok"

    def _pull_rpc(self, request: bytes, context) -> Iterator[bytes]:
        topic = request.decode("utf-8")
        with self._lock:
            q = self._pull_queues.setdefault(topic, queue.Queue())
        while context.is_active():
            try:
                item = q.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                yield item
            except BaseException:
                # Stream cancelled between pop and consume: requeue so the
                # batch isn't lost (at-least-once for pulled frames).
                q.put(item)
                raise

    # --- local wiring -----------------------------------------------------
    def subscribe(self, topic: str, handler: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def publish(self, topic: str, payload: Any) -> None:
        """Local publish: same fan-out as a remote Publish RPC, so the host
        process (e.g. the orchestrator) can use the server as its bus."""
        self._publish_rpc(_encode_envelope(topic, serialize_payload(payload)),
                          None)

    def enable_pull(self, topic: str) -> None:
        with self._lock:
            self._pull_queues.setdefault(topic, queue.Queue())

    def start(self) -> None:
        self._server.start()
        logger.info("bus server listening on %s", self.address)

    def close(self, grace: float = 0.5) -> None:
        self._server.stop(grace)


class GrpcBusClient:
    """Publishes payloads / pulls record-batch frames from a GrpcBusServer."""

    def __init__(self, target: str = "127.0.0.1:50551"):
        self.target = target
        self._channel = grpc.insecure_channel(
            target,
            options=[("grpc.max_receive_message_length", MAX_FRAME_BYTES),
                     ("grpc.max_send_message_length", MAX_FRAME_BYTES)])
        self._publish = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Publish", request_serializer=_identity,
            response_deserializer=_identity)
        self._pull = self._channel.unary_stream(
            f"/{SERVICE_NAME}/Pull", request_serializer=_identity,
            response_deserializer=_identity)

    def publish(self, topic: str, payload: Any) -> None:
        self._publish(_encode_envelope(topic, serialize_payload(payload)))

    def publish_frame(self, topic: str, frame: bytes) -> None:
        """Publish an already-encoded codec frame (record batches)."""
        self._publish(_encode_envelope(topic, frame))

    def pull(self, topic: str) -> Iterator[bytes]:
        """Server-streaming pull of raw payloads for a topic."""
        return self._pull(topic.encode("utf-8"))

    def close(self) -> None:
        self._channel.close()


class RemoteBus:
    """InMemoryBus-shaped facade over a GrpcBusClient for worker processes.

    `publish` is a Publish RPC to the host; `subscribe` starts a puller
    thread streaming the topic's queue and dispatching to local handlers
    (competing consumers: multiple workers pulling one topic split the
    stream — exactly the work-queue semantics of the reference's pubsub,
    `distributed/pubsub.go:149-254`).  Handler errors are retried
    `max_redeliveries` times, then dropped.
    """

    def __init__(self, target: str = "127.0.0.1:50551",
                 max_redeliveries: int = 3):
        self._client = GrpcBusClient(target)
        self.max_redeliveries = max_redeliveries
        self._handlers: Dict[str, list] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()

    def publish(self, topic: str, payload: Any) -> None:
        self._client.publish(topic, payload)

    def subscribe(self, topic: str,
                  handler: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)
            if topic in self._threads:
                return
            t = threading.Thread(target=self._pull_loop, args=(topic,),
                                 daemon=True, name=f"dct-bus-pull-{topic}")
            self._threads[topic] = t
            t.start()

    def _pull_loop(self, topic: str) -> None:
        while not self._stop.is_set():
            try:
                for frame in self._client.pull(topic):
                    if self._stop.is_set():
                        return
                    self._dispatch(topic, frame)
            except grpc.RpcError as e:
                if self._stop.is_set():
                    return
                logger.warning("pull stream for %s dropped (%s); "
                               "reconnecting", topic, e.code()
                               if hasattr(e, "code") else e)
                self._stop.wait(1.0)

    def _dispatch(self, topic: str, frame: bytes) -> None:
        try:
            payload = json.loads(frame.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            logger.error("dropping undecodable message on %s", topic)
            return
        with self._lock:
            handlers = list(self._handlers.get(topic, []))
        for handler in handlers:
            for attempt in range(self.max_redeliveries + 1):
                try:
                    handler(payload)
                    break
                except Exception as e:
                    logger.warning("handler error on %s (attempt %d/%d): %s",
                                   topic, attempt + 1,
                                   self.max_redeliveries + 1, e)

    def start(self) -> None:
        return None  # threads start on subscribe

    def close(self) -> None:
        self._stop.set()
        self._client.close()
        for t in self._threads.values():
            t.join(timeout=2.0)
