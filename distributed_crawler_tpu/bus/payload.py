"""Shared envelope serialization for every bus transport."""

from __future__ import annotations

import json
from typing import Any


def serialize_payload(payload: Any) -> bytes:
    """bytes pass through; objects with to_dict() are unwrapped; everything
    else is UTF-8 JSON — the one encoding rule for InMemoryBus, the gRPC
    server's local publish, and the gRPC client."""
    if isinstance(payload, bytes):
        return payload
    if hasattr(payload, "to_dict"):
        payload = payload.to_dict()
    return json.dumps(payload, ensure_ascii=False).encode("utf-8")
