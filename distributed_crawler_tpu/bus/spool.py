"""Broker WAL spool: the durable memory of the gRPC bus.

The reference inherited broker durability for free — its pubsub rode a
Dapr sidecar backed by Redis, so the *broker* survived restarts and
redelivered (`distributed/pubsub.go:157-254`).  Our `GrpcBusServer` keeps
every pull queue and the in-flight ledger in process memory, and it
usually lives INSIDE the orchestrator process, so a coordinator restart
used to take every undelivered frame down with it.  This module is the
broker-side analog of the orchestrator's `CrawlJournal`
(`orchestrator/journal.py`):

- :class:`TopicSpool` — one append-only JSONL WAL per pull topic
  recording ``enq`` (frame enters the queue), ``rq`` (requeue, attempt
  count bumped), ``ack`` (frame done), and ``dead`` (frame dead-lettered)
  events.  Appends are flushed per event and fsynced in batches
  (``fsync_every``); replay folds the surviving events into the exact
  queued + unacked-in-flight frame set, attempt counts included, with a
  torn tail line (crash mid-append) skipped, not fatal.  Compaction
  rewrites the WAL as pure ``enq`` events of the live frames — atomic
  (tmp + fsync + rename) and triggered once the acked/dead prefix
  dominates the live set.
- :class:`DeadLetterSpool` — the REAL dead-letter queue: frames that
  exhausted ``max_attempts`` (or a local handler's retry budget) land in
  a per-topic JSONL spool with their payload, attempt count, and reason,
  instead of being logged and dropped.  ``tools/dlq.py`` lists, inspects,
  and replays them back onto their topic; replays are marked with a
  ``rpl`` event so an entry is re-driven at most deliberately.
- :class:`BusSpool` — the facade `GrpcBusServer(spool_dir=...)` holds:
  per-topic spools created on demand, plus the DLQ.

Frame ids (``fid``) are minted at enqueue and stay stable across broker
generations — a restarted broker redelivers the same frame under the
same id, so consumer-side dedup (post_uid windows, idempotent per-batch
writeback) has a stable key to work with.

Topic names are encoded with ``urllib.parse.quote`` for directory names,
so replay can recover the exact topic string from the filesystem alone.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional
from urllib.parse import quote, unquote

logger = logging.getLogger("dct.bus.spool")

WAL_FILE = "wal.jsonl"
TOPICS_DIR = "topics"
DLQ_DIR = "dlq"

DEFAULT_FSYNC_EVERY = 16
DEFAULT_COMPACT_EVERY = 256


def _encode_topic(topic: str) -> str:
    return quote(topic, safe="-_.")


def _decode_topic(name: str) -> str:
    return unquote(name)


def new_frame_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class SpooledFrame:
    """One live (queued or in-flight-at-crash) frame recovered by replay."""

    fid: str
    payload: bytes
    attempts: int = 0


@dataclass
class DeadLetter:
    """One dead-lettered frame, folded from the DLQ spool."""

    fid: str
    topic: str
    payload: bytes
    attempts: int = 0
    reason: str = ""
    ts: float = 0.0
    replayed: bool = False

    def meta(self) -> Dict[str, Any]:
        """Payload-free summary (the /dlq listing row)."""
        return {"id": self.fid, "topic": self.topic,
                "attempts": self.attempts, "reason": self.reason,
                "ts": self.ts, "replayed": self.replayed,
                "bytes": len(self.payload)}


def _read_lines(path: str) -> List[str]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().splitlines()
    except OSError:
        return []


def _fold_lines(path: str) -> List[Dict[str, Any]]:
    """Parse surviving JSONL events; a torn TAIL line is dropped (crash
    mid-append), a torn interior line is skipped with a warning."""
    lines = _read_lines(path)
    out: List[Dict[str, Any]] = []
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                logger.warning("spool %s: dropping torn tail line", path)
            else:
                logger.warning("spool %s: skipping corrupt line %d",
                               path, i + 1)
    return out


class TopicSpool:
    """Append-only WAL + live-frame mirror for one pull topic."""

    def __init__(self, root: str, topic: str,
                 fsync_every: int = DEFAULT_FSYNC_EVERY,
                 compact_every: int = DEFAULT_COMPACT_EVERY):
        self.topic = topic
        self.dir = os.path.join(root, TOPICS_DIR, _encode_topic(topic))
        self.fsync_every = max(1, fsync_every)
        self.compact_every = max(1, compact_every)
        self._lock = threading.Lock()
        self._fh = None
        self._closed = False
        self._since_fsync = 0
        self._since_compact = 0
        # fid -> SpooledFrame; insertion order IS queue order (a requeue
        # moves the frame to the tail, matching the live queue).
        self._live: "OrderedDict[str, SpooledFrame]" = OrderedDict()
        os.makedirs(self.dir, exist_ok=True)
        self._load()

    @property
    def wal_path(self) -> str:
        return os.path.join(self.dir, WAL_FILE)

    # -- recovery -----------------------------------------------------------
    def _load(self) -> None:
        for ev in _fold_lines(self.wal_path):
            self._fold(ev)

    def _fold(self, ev: Dict[str, Any]) -> None:
        kind = ev.get("k")
        fid = str(ev.get("id", ""))
        if not fid:
            return
        if kind == "enq":
            try:
                payload = base64.b64decode(ev.get("d", ""))
            except (ValueError, TypeError):
                logger.warning("spool %s: undecodable enq payload (id=%s)",
                               self.topic, fid)
                return
            self._live[fid] = SpooledFrame(fid, payload,
                                           int(ev.get("a", 0) or 0))
        elif kind == "rq":
            frame = self._live.get(fid)
            if frame is not None:
                frame.attempts = int(ev.get("a", frame.attempts) or 0)
                self._live.move_to_end(fid)
        elif kind in ("ack", "dead"):
            self._live.pop(fid, None)
        # Unknown kinds ignored: spools must be forward-readable.

    def replay(self) -> List[SpooledFrame]:
        """The live frame set in queue order — a pure function of the
        on-disk bytes at construction plus the appends since (calling it
        twice returns the same recovery; asserted by tests)."""
        with self._lock:
            return [SpooledFrame(f.fid, f.payload, f.attempts)
                    for f in self._live.values()]

    def live_count(self) -> int:
        with self._lock:
            return len(self._live)

    # -- writing ------------------------------------------------------------
    def _append_locked(self, ev: Dict[str, Any]) -> None:
        if self._closed:
            raise RuntimeError(f"spool for {self.topic!r} is closed")
        if self._fh is None:
            # WAL semantics: file I/O under the writer lock IS the
            # serialization point (caller holds _lock, `_locked` suffix).
            self._fh = open(self.wal_path, "a",  # crawlint: disable=LCK001,LCK002
                            encoding="utf-8")
        self._fh.write(json.dumps(ev) + "\n")
        self._fh.flush()
        self._since_fsync += 1
        if self._since_fsync >= self.fsync_every:
            os.fsync(self._fh.fileno())
            self._since_fsync = 0
        self._since_compact += 1

    def enqueue(self, payload: bytes, attempts: int = 0,
                fid: Optional[str] = None) -> str:
        fid = fid or new_frame_id()
        ev = {"k": "enq", "id": fid,
              "d": base64.b64encode(payload).decode("ascii")}
        if attempts:
            ev["a"] = attempts
        with self._lock:
            self._append_locked(ev)
            self._live[fid] = SpooledFrame(fid, payload, attempts)
        return fid

    def requeue(self, fid: str, attempts: int) -> None:
        with self._lock:
            self._append_locked({"k": "rq", "id": fid, "a": attempts})
            frame = self._live.get(fid)
            if frame is not None:
                frame.attempts = attempts
                self._live.move_to_end(fid)

    def ack(self, fid: str) -> None:
        with self._lock:
            self._append_locked({"k": "ack", "id": fid})
            self._live.pop(fid, None)
            self._maybe_compact_locked()

    def remove_dead(self, fid: str) -> None:
        """Drop a frame that moved to the dead-letter spool (the DLQ
        append happens FIRST, so a crash between the two redelivers
        instead of losing the frame)."""
        with self._lock:
            self._append_locked({"k": "dead", "id": fid})
            self._live.pop(fid, None)
            self._maybe_compact_locked()

    # -- compaction ---------------------------------------------------------
    def _maybe_compact_locked(self) -> None:
        # Compact once the acked/dead prefix dominates: enough events
        # since the last rewrite AND at least half of them are now dead
        # weight (live*2 <= events means >= half the lines fold to
        # nothing on replay).
        if self._since_compact >= self.compact_every \
                and len(self._live) * 2 <= self._since_compact:
            self._compact_locked()

    def compact(self) -> None:
        """Force a WAL rewrite down to the live frames (tests/shutdown)."""
        with self._lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self.wal_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:  # crawlint: disable=LCK002
            for frame in self._live.values():
                ev = {"k": "enq", "id": frame.fid,
                      "d": base64.b64encode(frame.payload).decode("ascii")}
                if frame.attempts:
                    ev["a"] = frame.attempts
                f.write(json.dumps(ev) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None  # crawlint: disable=LCK001
        # The rename IS the commit point: a crash before it replays the
        # old WAL, a crash after it replays the rewritten one — both fold
        # to the same live set.
        os.replace(tmp, self.wal_path)
        self._since_compact = 0
        self._since_fsync = 0

    def close(self, compact: bool = False) -> None:
        with self._lock:
            if compact and not self._closed:
                self._compact_locked()
            if self._fh is not None:
                try:
                    if self._since_fsync:
                        os.fsync(self._fh.fileno())
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None  # crawlint: disable=LCK001
            self._closed = True


class DeadLetterSpool:
    """Per-topic persisted dead letters + replay markers.

    Replayed entries are audit history, not queue content: once their
    count passes ``replayed_retention`` the file is compacted — pending
    entries all survive, only the newest ``replayed_retention`` replayed
    ones are kept — so a broker that lives through many poison bursts
    and replays doesn't grow (or re-parse) an unbounded file forever.

    ``replayed_retention=None`` disables compaction entirely: the
    rewrite-and-rename is only safe for the instance that OWNS the spool
    (the broker) — a second process compacting concurrently (e.g.
    ``tools/dlq.py`` against a live broker's directory) could erase a
    dead letter appended between its fold and its rename, so the tool
    runs with compaction off."""

    def __init__(self, root: str,
                 replayed_retention: Optional[int] = 256):
        self.dir = os.path.join(root, DLQ_DIR)
        self.replayed_retention = replayed_retention if \
            replayed_retention is None else max(0, replayed_retention)
        self._lock = threading.Lock()
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, topic: str) -> str:
        return os.path.join(self.dir, _encode_topic(topic) + ".jsonl")

    def topics(self) -> List[str]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        return sorted(_decode_topic(n[:-6]) for n in names
                      if n.endswith(".jsonl"))

    def append(self, topic: str, fid: str, payload: bytes,
               attempts: int, reason: str) -> None:
        ev = {"k": "dead", "id": fid, "ts": time.time(),
              "a": attempts, "r": reason,
              "d": base64.b64encode(payload).decode("ascii")}
        with self._lock:
            with open(self._path(topic), "a",  # crawlint: disable=LCK002
                      encoding="utf-8") as f:
                f.write(json.dumps(ev) + "\n")
                f.flush()
                os.fsync(f.fileno())

    def mark_replayed(self, topic: str, fid: str) -> None:
        ev = {"k": "rpl", "id": fid, "ts": time.time()}
        with self._lock:
            with open(self._path(topic), "a",  # crawlint: disable=LCK002
                      encoding="utf-8") as f:
                f.write(json.dumps(ev) + "\n")
                f.flush()
                os.fsync(f.fileno())
        self._maybe_compact(topic)

    def _maybe_compact(self, topic: str) -> None:
        if self.replayed_retention is None:
            return  # not the owning instance; never rewrite (see class doc)
        # Fold AND rewrite under one lock hold: an append landing between
        # the read and the rename would otherwise be silently dropped.
        with self._lock:
            entries = self.entries(topic)
            replayed = [e for e in entries if e.replayed]
            if len(replayed) <= self.replayed_retention:
                return
            drop = {e.fid for e in replayed[:len(replayed)
                                            - self.replayed_retention]}
            path = self._path(topic)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:  # crawlint: disable=LCK002
                for e in entries:
                    if e.fid in drop:
                        continue
                    f.write(json.dumps({
                        "k": "dead", "id": e.fid, "ts": e.ts,
                        "a": e.attempts, "r": e.reason,
                        "d": base64.b64encode(e.payload).decode("ascii")})
                        + "\n")
                    if e.replayed:
                        f.write(json.dumps({"k": "rpl", "id": e.fid,
                                            "ts": e.ts}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def entries(self, topic: str) -> List[DeadLetter]:
        """Folded dead letters for one topic, oldest first."""
        out: "OrderedDict[str, DeadLetter]" = OrderedDict()
        for ev in _fold_lines(self._path(topic)):
            fid = str(ev.get("id", ""))
            if not fid:
                continue
            if ev.get("k") == "dead":
                try:
                    payload = base64.b64decode(ev.get("d", ""))
                except (ValueError, TypeError):
                    logger.warning("dlq %s: undecodable payload (id=%s)",
                                   topic, fid)
                    continue
                out[fid] = DeadLetter(
                    fid=fid, topic=topic, payload=payload,
                    attempts=int(ev.get("a", 0) or 0),
                    reason=str(ev.get("r", "") or ""),
                    ts=float(ev.get("ts", 0.0) or 0.0))
            elif ev.get("k") == "rpl" and fid in out:
                out[fid].replayed = True
        return list(out.values())

    def get(self, topic: str, fid: str) -> Optional[DeadLetter]:
        for entry in self.entries(topic):
            if entry.fid == fid:
                return entry
        return None

    def snapshot(self, topic: Optional[str] = None,
                 fid: Optional[str] = None,
                 max_entries: int = 50) -> Dict[str, Any]:
        """The /dlq body: per-topic counts + newest entry metadata; with
        ``fid`` set, that entry's full payload (base64) rides along."""
        topics = [topic] if topic else self.topics()
        body: Dict[str, Any] = {"topics": {}}
        for t in topics:
            entries = self.entries(t)
            body["topics"][t] = {
                "count": len(entries),
                "pending": sum(1 for e in entries if not e.replayed),
                "entries": [e.meta() for e in entries[-max_entries:]],
            }
            if fid:
                hit = next((e for e in entries if e.fid == fid), None)
                if hit is not None:
                    body["entry"] = {
                        **hit.meta(),
                        "payload_b64":
                            base64.b64encode(hit.payload).decode("ascii"),
                    }
        return body


class BusSpool:
    """Everything durable about one broker: per-topic WALs + the DLQ."""

    def __init__(self, root: str,
                 fsync_every: int = DEFAULT_FSYNC_EVERY,
                 compact_every: int = DEFAULT_COMPACT_EVERY):
        if not root:
            raise ValueError("spool root cannot be empty")
        self.root = root
        self.fsync_every = fsync_every
        self.compact_every = compact_every
        self._lock = threading.Lock()
        self._topics: Dict[str, TopicSpool] = {}
        self._closed = False
        os.makedirs(os.path.join(root, TOPICS_DIR), exist_ok=True)
        self.dlq = DeadLetterSpool(root)

    def existing_topics(self) -> List[str]:
        """Topics with an on-disk WAL — what a restarted broker rebuilds."""
        try:
            names = os.listdir(os.path.join(self.root, TOPICS_DIR))
        except OSError:
            return []
        return sorted(_decode_topic(n) for n in names
                      if os.path.exists(os.path.join(
                          self.root, TOPICS_DIR, n, WAL_FILE)))

    def topic(self, topic: str) -> TopicSpool:
        with self._lock:
            if self._closed:
                # A closed BusSpool must refuse even first-enqueue topics:
                # minting a fresh open TopicSpool here would let a publish
                # racing a broker kill() journal a frame into a WAL the
                # next generation has already read — acked but delivered
                # by no live generation.
                raise RuntimeError("bus spool is closed")
            ts = self._topics.get(topic)
            if ts is None:
                ts = TopicSpool(self.root, topic,
                                fsync_every=self.fsync_every,
                                compact_every=self.compact_every)
                self._topics[topic] = ts
            return ts

    # -- the broker-facing protocol -----------------------------------------
    def enqueue(self, topic: str, payload: bytes,
                attempts: int = 0) -> str:
        return self.topic(topic).enqueue(payload, attempts=attempts)

    def requeue(self, topic: str, fid: str, attempts: int) -> None:
        if fid:
            self.topic(topic).requeue(fid, attempts)

    def ack(self, topic: str, fid: str) -> None:
        if fid:
            self.topic(topic).ack(fid)

    def dead(self, topic: str, fid: str, payload: bytes,
             attempts: int, reason: str) -> str:
        """Move a frame to the DLQ (durably FIRST, then drop it from the
        topic WAL — a crash between the two duplicates a dead letter,
        never loses one).  An empty ``fid`` means the frame was never in
        a topic WAL (a local-handler dead letter on a fan-out topic): it
        gets a minted id for the DLQ entry, and the topic WAL is left
        untouched — writing there would conjure a phantom pull topic
        that a restarted broker rebuilds and nobody ever drains."""
        journaled = fid
        fid = fid or new_frame_id()
        self.dlq.append(topic, fid, payload, attempts, reason)
        if journaled:
            self.topic(topic).remove_dead(journaled)
        return fid

    def replay(self, topic: str) -> List[SpooledFrame]:
        return self.topic(topic).replay()

    def close(self, compact: bool = True) -> None:
        with self._lock:
            self._closed = True
            topics = list(self._topics.values())
        for ts in topics:
            ts.close(compact=compact)
