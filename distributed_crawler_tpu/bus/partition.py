"""Consistent-hash partitioned message bus: scale the control plane 1→N.

PR 10 made the single broker durable; at millions-of-users traffic ONE
`GrpcBusServer` (and the one orchestrator queue feeding it) is the
throughput and fan-out ceiling the ROADMAP names.  The reference ran the
sharded shape natively — Dapr pubsub partitions over Redis Streams with
a PostgreSQL frontier (PAPER.md §1 layers 3/6) — and this module brings
it in-tree without touching the broker itself: every shard is a stock
`GrpcBusServer` with its OWN spool directory, so PR 10's kill/resume
semantics apply per shard unchanged.

Three pieces:

- :class:`ShardMap` — a stable consistent-hash ring over shard ids
  (`hashlib` points, never Python's salted ``hash()``, so the same key
  maps to the same shard in every process and across restarts).  Adding
  or removing one shard moves only ~1/N of the keyspace — the property
  that makes resharding an incremental migration instead of a full
  redeal.
- :func:`routing_key` — the per-frame key for *routed* (pull/work)
  topics: ``post_uid`` / work-item id / batch id, with the work-queue
  special case of the page's CHANNEL (the sharded-frontier contract:
  one channel's pages always ride one dispatch lane).  Redeliveries of
  one item therefore always land on the same shard, preserving the
  per-item ordering + idempotence discipline from PRs 7/10.
- :class:`PartitionedBus` — N bus endpoints (``RemoteBus`` clients or
  in-process servers/handles) behind the existing bus interface.
  Routed topics hash to exactly one shard; fan-out topics
  (:data:`BROADCAST_TOPICS`) broadcast to EVERY shard (a dead shard
  cannot black-hole telemetry) and subscribers dedupe by a broadcast id
  stamped at publish time, so each logical frame is delivered once.
  Every shard gets its own :class:`~.outbox.DurableOutbox` (its own
  spill WAL when configured) and its own circuit-breaker target
  (``bus-<i>``): a dead shard's frames PARK in that shard's outbox
  until its generation returns — never a silent re-hash to a live
  shard, which would break same-key-same-shard ordering.

The misconfiguration this module makes impossible: two shards sharing
one WAL directory (spool or outbox spill) would cross-contaminate each
other's crash recovery — :func:`validate_shard_spool_dirs` rejects it
loudly at config time, and the derivation helpers only ever produce
distinct per-shard subdirectories.

``python -m distributed_crawler_tpu.bus.partition --bench-child ...`` is
the bus-throughput bench leg's per-shard child (one broker + publisher +
consumer per process over loopback gRPC; `bench.py` aggregates 1/2/4
shards into the ``bus_frames_per_s_shards*`` rows).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import uuid
from bisect import bisect_right
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional

from ..utils import trace
from ..utils.metrics import REGISTRY, MetricsRegistry
from .messages import (
    TOPIC_ALERTS,
    TOPIC_CHAOS,
    TOPIC_CLUSTERS,
    TOPIC_ORCHESTRATOR,
    TOPIC_SPANS,
    TOPIC_TRANSCRIPTS,
    TOPIC_WORKER_STATUS,
)
from .outbox import DurableOutbox, OutboxConfig

logger = logging.getLogger("dct.bus.partition")

# Fan-out (announce) topics: every subscriber must see every frame, and
# no frame may depend on one shard's liveness — publish BROADCASTS to
# all shards, subscribe attaches to all shards, and the per-frame
# broadcast id dedupes so each logical frame reaches a handler once.
# Everything else is a routed (work/pull) topic: exactly one shard per
# frame, chosen by routing_key().
BROADCAST_TOPICS = frozenset({
    TOPIC_WORKER_STATUS, TOPIC_ORCHESTRATOR, TOPIC_CHAOS, TOPIC_SPANS,
    TOPIC_ALERTS, TOPIC_CLUSTERS, TOPIC_TRANSCRIPTS,
})

# The broadcast-id stamp: follows the trace.inject precedent (typed
# messages tolerate extra envelope keys); stripped before handlers see
# the payload.
_BCAST_KEY = "_pbus_bcast"

DEFAULT_RING_REPLICAS = 64


def default_shard_ids(count: int) -> List[str]:
    """The canonical shard naming (chaos targets, spool subdirs, breaker
    targets all use these): ``bus-0`` .. ``bus-<n-1>``."""
    return [f"bus-{i}" for i in range(count)]


def channel_of(url: str) -> str:
    """Channel name from a frontier URL: the last non-empty path segment,
    lowercased (t.me/<channel>, youtube.com/@<handle>, or a bare channel
    name all resolve the same way).  The orchestrator's cluster guide and
    the sharded frontier share this one rule, so 'the same channel' means
    the same thing to both."""
    tail = url.rstrip("/").rsplit("/", 1)[-1]
    return tail.partition("?")[0].lstrip("@").lower()


class ShardMap:
    """Stable consistent-hash ring over shard ids.

    Each shard owns ``replicas`` points on a 64-bit ring derived from
    ``md5(f"{shard}#{replica}")`` — process-independent and
    restart-stable by construction.  ``shard_for(key)`` walks clockwise
    from ``md5(key)`` to the next point.  With one shard added or
    removed, only the keys between the moved points change owners
    (~1/N of the keyspace; pinned by tests/test_bus_partition.py).
    """

    def __init__(self, shard_ids: Iterable[str],
                 replicas: int = DEFAULT_RING_REPLICAS):
        self.shard_ids = list(shard_ids)
        if not self.shard_ids:
            raise ValueError("ShardMap needs at least one shard id")
        if len(set(self.shard_ids)) != len(self.shard_ids):
            raise ValueError(
                f"duplicate shard ids in {self.shard_ids!r}")
        self.replicas = max(1, int(replicas))
        points: List[tuple] = []
        for sid in self.shard_ids:
            for r in range(self.replicas):
                points.append((self._point(f"{sid}#{r}"), sid))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [s for _, s in points]

    @staticmethod
    def _point(key: str) -> int:
        # hashlib, NOT hash(): Python's str hash is salted per process,
        # which would re-deal the ring on every restart.
        return int.from_bytes(
            hashlib.md5(key.encode("utf-8")).digest()[:8], "big")

    def shard_for(self, key: str) -> str:
        i = bisect_right(self._points, self._point(str(key)))
        if i >= len(self._points):
            i = 0
        return self._owners[i]

    def spread(self, keys: Iterable[str]) -> Dict[str, int]:
        """Key count per shard (tests + the /shards ring summary)."""
        out = {sid: 0 for sid in self.shard_ids}
        for k in keys:
            out[self.shard_for(k)] += 1
        return out


def routing_key(topic: str, payload: Any) -> str:
    """The stable per-frame routing key for a routed topic.

    Work-queue messages route by the page's CHANNEL (the sharded
    frontier: one channel's pages — and every redelivery of them — ride
    one shard's dispatch lane); results route by their work-item id;
    record/audio batches by batch id; single-record frames by
    ``post_uid``/``media_id``.  Anything unrecognized routes by the
    TOPIC name: all frames of an unknown topic share one shard, which
    keeps them ordered rather than scattered.
    """
    if hasattr(payload, "to_dict"):
        payload = payload.to_dict()
    if isinstance(payload, (bytes, bytearray)):
        # Pre-encoded codec frames carry no inspectable key; identical
        # bytes (a redelivered frame) still hash identically.
        return hashlib.md5(bytes(payload)).hexdigest()
    if not isinstance(payload, Mapping):
        return topic
    item = payload.get("work_item") or payload.get("item")
    if isinstance(item, Mapping):
        url = str(item.get("url") or "")
        if url:
            return channel_of(url)
        if item.get("id"):
            return str(item["id"])
    result = payload.get("work_result") or payload.get("result")
    if isinstance(result, Mapping) and result.get("work_item_id"):
        return str(result["work_item_id"])
    for key in ("work_item_id", "post_uid", "batch_id", "media_id"):
        if payload.get(key):
            return str(payload[key])
    return topic


def shard_spool_dirs(base_dir: str,
                     shard_ids: Iterable[str]) -> Dict[str, str]:
    """Derive one spool (or outbox-spill) directory per shard under
    ``base_dir`` — distinct by construction, validated anyway."""
    dirs = {sid: os.path.join(base_dir, sid) for sid in shard_ids}
    validate_shard_spool_dirs(dirs)
    return dirs


def validate_shard_spool_dirs(dirs_by_shard: Mapping[str, str]) -> None:
    """LOUD config-time rejection of shared per-shard WAL directories.

    One spool dir across two shards would let each generation replay the
    other's frames (cross-contaminated WAL recovery = duplicate
    delivery); the rule applies equally to outbox spill WALs.  Empty
    entries are rejected too: durability that silently isn't is exactly
    the misconfiguration class the loud-validation rule exists for.
    """
    dirs = dict(dirs_by_shard)
    empty = sorted(sid for sid, d in dirs.items() if not str(d or "").strip())
    if empty:
        raise ValueError(
            f"bus durability is enabled but shard(s) {', '.join(empty)} "
            f"have no spool directory — every shard needs its OWN WAL dir")
    normalized: Dict[str, str] = {}
    for sid, d in dirs.items():
        key = os.path.normpath(os.path.abspath(str(d)))
        if key in normalized:
            raise ValueError(
                f"bus shards {normalized[key]!r} and {sid!r} share one "
                f"spool directory {d!r} — a shared WAL cross-contaminates "
                f"crash recovery; give every shard its own directory")
        normalized[key] = sid


class _BroadcastDedupe:
    """Bounded seen-set for broadcast ids: N shard copies of one fan-out
    frame collapse to a single handler delivery."""

    def __init__(self, window: int = 4096):
        self._window = max(16, int(window))
        self._seen: set = set()
        self._order: deque = deque()
        self._lock = threading.Lock()

    def first_sighting(self, bcast_id: str) -> bool:
        with self._lock:
            if bcast_id in self._seen:
                return False
            self._seen.add(bcast_id)
            self._order.append(bcast_id)
            while len(self._order) > self._window:
                self._seen.discard(self._order.popleft())
            return True


class PartitionedBus:
    """N bus endpoints behind the one-bus interface.

    ``endpoints`` maps shard id -> transport (a ``RemoteBus`` dialing
    that shard's broker, or an in-process server/handle for co-hosted
    rigs).  Publishes flow through one :class:`DurableOutbox` PER SHARD
    (head-of-line, bounded, optional spill WAL, per-shard circuit
    breaker target ``<shard id>``), so a dead shard parks its frames in
    its own outbox until that shard's generation returns; the ring is
    never consulted twice for one frame (no failover re-hash).

    Subscribe semantics: routed topics register the handler on EVERY
    shard (competing consumers per shard queue — work from any shard
    reaches any worker); broadcast topics register a deduping wrapper on
    every shard so each logical frame is delivered exactly once even
    though the publish fanned out N ways.
    """

    def __init__(self, endpoints: Mapping[str, Any],
                 shard_map: Optional[ShardMap] = None,
                 outbox: Optional[Callable[[str], OutboxConfig]] = None,
                 name: str = "pbus",
                 registry: MetricsRegistry = REGISTRY,
                 broadcast_topics: frozenset = BROADCAST_TOPICS,
                 dedupe_window: int = 4096,
                 close_endpoints: bool = True):
        if not endpoints:
            raise ValueError("PartitionedBus needs at least one endpoint")
        self._endpoints: Dict[str, Any] = dict(endpoints)
        self.shard_map = shard_map or ShardMap(list(self._endpoints))
        extra = set(self.shard_map.shard_ids) ^ set(self._endpoints)
        if extra:
            raise ValueError(
                f"shard map and endpoints disagree on shard ids: "
                f"{sorted(extra)}")
        self.name = name
        self.broadcast_topics = frozenset(broadcast_topics)
        self._close_endpoints = close_endpoints
        self._lock = threading.Lock()
        self._pull_topics: List[str] = []
        self._routed_counts: Dict[tuple, int] = {}
        self._broadcast_count = 0
        self._dedupe_window = dedupe_window
        self.m_routed = registry.counter(
            "bus_shard_frames_total",
            "frames routed to one shard of the partitioned bus "
            "(bus/partition.py; key = routing_key)")
        self.m_broadcast = registry.counter(
            "bus_shard_broadcast_total",
            "fan-out frames broadcast to every shard of the "
            "partitioned bus")
        # One outbox + one breaker target per shard: the failover story.
        # A shared spill directory across shards is rejected exactly like
        # a shared broker spool (validate_shard_spool_dirs).
        cfgs = {sid: (outbox(sid) if callable(outbox) else OutboxConfig())
                for sid in self._endpoints}
        spill = {sid: c.dir for sid, c in cfgs.items() if c.dir}
        if spill:
            missing = sorted(set(self._endpoints) - set(spill))
            if missing:
                raise ValueError(
                    f"outbox spill WALs configured for only part of the "
                    f"fleet (shard(s) {', '.join(missing)} have none) — "
                    f"durability must cover every shard or none")
            validate_shard_spool_dirs(spill)
        self._outboxes: Dict[str, DurableOutbox] = {}
        for sid, ep in self._endpoints.items():
            self._outboxes[sid] = DurableOutbox(
                self._sender(ep), cfgs[sid], name=f"{name}-{sid}",
                registry=registry, breaker_target=sid)

    @staticmethod
    def _sender(ep) -> Callable[[str, Any], None]:
        def _send(topic: str, payload: Any) -> None:
            ep.publish(topic, payload)
        return _send

    # -- publish side --------------------------------------------------------
    def publish(self, topic: str, payload: Any) -> None:
        # Unwrap to the dict form first (the serialize_payload rule),
        # then stamp the trace parent HERE (the outbox flusher thread
        # has no span context) — one stamp keeps the N broadcast copies
        # identical, and the inner transports' inject is a no-op on an
        # already-stamped payload.
        if hasattr(payload, "to_dict"):
            payload = payload.to_dict()
        payload = trace.inject(payload)
        if topic in self.broadcast_topics:
            if isinstance(payload, dict):
                payload = {**payload, _BCAST_KEY: uuid.uuid4().hex}
            # Fan-out delivery needs AT LEAST ONE shard copy to land
            # (subscribers attach to every shard and dedupe), so a
            # minority of full outboxes degrades the redundancy, never
            # the publish: raising mid-loop after siblings already
            # enqueued would make the caller retry a frame that WILL be
            # delivered — and the retry's fresh broadcast id would
            # duplicate it.  Only an all-targets rejection raises.
            #
            # A shard already known-dead (breaker OPEN) is skipped, not
            # parked-into: sibling copies deliver NOW, and a copy
            # parked for minutes outlives the dedupe window and would
            # replay at restart as a STALE duplicate command/alert —
            # fan-out frames degrade promptness, never correctness
            # (bus/messages.py), so redundancy is not worth stale
            # replay (and parked broadcast copies would crowd routed
            # frames out of the dead shard's bounded outbox).  A TOTAL
            # outage (every breaker open) still buffers everywhere:
            # with no live copy possible, eventual delivery beats loss
            # — the single-broker durable behavior.
            open_shards = {sid for sid, ob in self._outboxes.items()
                           if ob.circuit_state == "open"}
            targets = [sid for sid in self._endpoints
                       if sid not in open_shards] \
                or list(self._endpoints)
            if open_shards and len(targets) < len(self._endpoints):
                logger.debug(
                    "broadcast on %s skipping open-breaker shard(s) %s",
                    topic, sorted(open_shards))
            errors: List[tuple] = []
            for sid in targets:
                try:
                    self._outboxes[sid].publish(topic, payload)
                except Exception as e:  # OutboxFull, closed outbox
                    errors.append((sid, e))
            if len(errors) == len(targets):
                raise errors[0][1]
            if errors:
                logger.warning(
                    "broadcast on %s skipped %d/%d shard outbox(es) "
                    "(%s); the live copies still deliver", topic,
                    len(errors), len(targets),
                    "; ".join(f"{sid}: {e}" for sid, e in errors))
            with self._lock:
                self._broadcast_count += 1
            self.m_broadcast.labels(topic=topic).inc()
            return
        key = routing_key(topic, payload)
        sid = self.shard_map.shard_for(key)
        self._outboxes[sid].publish(topic, payload)
        self.m_routed.labels(shard=sid, topic=topic).inc()
        with self._lock:
            self._routed_counts[(sid, topic)] = \
                self._routed_counts.get((sid, topic), 0) + 1

    def shard_for_key(self, key: str) -> str:
        return self.shard_map.shard_for(key)

    # -- subscribe side ------------------------------------------------------
    def subscribe(self, topic: str, handler: Callable[..., None],
                  manual_ack: Optional[bool] = None) -> None:
        if topic in self.broadcast_topics:
            if manual_ack:
                raise ValueError(
                    f"manual-ack subscription on broadcast topic "
                    f"{topic!r}: fan-out frames are auto-ack by design")
            handler = self._dedupe_wrapper(handler)
            manual_ack = None
        for ep in self._endpoints.values():
            self._ep_subscribe(ep, topic, handler, manual_ack)

    @staticmethod
    def _ep_subscribe(ep, topic, handler, manual_ack) -> None:
        if manual_ack is None:
            ep.subscribe(topic, handler)
            return
        try:
            ep.subscribe(topic, handler, manual_ack=manual_ack)
        except TypeError:
            # Local servers/handles take (topic, handler) only; their
            # dispatch has no ack channel, so the kwarg is advisory.
            ep.subscribe(topic, handler)

    def _dedupe_wrapper(self, handler: Callable[[Any], None]  # crawlint: disable=BUS004
                        ) -> Callable[[Any], None]:
        # No payload_span here: this wrapper runs INSIDE the endpoint
        # transport's own `bus.deliver` span (InMemoryBus/RemoteBus/
        # GrpcBusServer all wrap dispatch) — a second span would
        # double-count the delivery hop in every trace.
        dedupe = _BroadcastDedupe(self._dedupe_window)

        def _deliver(payload: Any) -> None:  # crawlint: disable=BUS004
            if isinstance(payload, dict):
                bcast_id = payload.get(_BCAST_KEY)
                if bcast_id is not None:
                    if not dedupe.first_sighting(str(bcast_id)):
                        return  # another shard's copy already delivered
                    payload = {k: v for k, v in payload.items()
                               if k != _BCAST_KEY}
            handler(payload)

        return _deliver

    # -- the rest of the bus interface --------------------------------------
    def enable_pull(self, topic: str) -> None:
        with self._lock:
            if topic not in self._pull_topics:
                self._pull_topics.append(topic)
        for ep in self._endpoints.values():
            fn = getattr(ep, "enable_pull", None)
            if callable(fn):
                fn(topic)

    def pending_count(self, topic: str) -> int:
        total = 0
        for ep in self._endpoints.values():
            fn = getattr(ep, "pending_count", None)
            if callable(fn):
                total += int(fn(topic))
        return total

    def flush_local(self, timeout_s: float = 5.0) -> bool:
        ok = True
        for ep in self._endpoints.values():
            fn = getattr(ep, "flush_local", None)
            if callable(fn):
                ok = fn(timeout_s) and ok
        return ok

    def drain(self, timeout_s: float = 30.0, poll_s: float = 0.2) -> bool:
        """Outboxes first (a parked frame is pending work the brokers
        can't see yet), then every shard against one shared deadline."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        ok = self.drain_outboxes(timeout_s)
        for ep in self._endpoints.values():
            fn = getattr(ep, "drain", None)
            if callable(fn):
                left = max(0.1, deadline - _time.monotonic())
                ok = fn(timeout_s=left, poll_s=poll_s) and ok
        return ok

    def dlq_snapshot(self, topic: Optional[str] = None,
                     id: Optional[str] = None) -> Dict[str, Any]:
        """Merged /dlq body: per-shard bodies under ``shards``, plus a
        top-level ``topics`` fold (counts summed, newest entries
        shard-stamped) so `tools/dlq.py`'s live mode reads a sharded
        broker the same way it reads one."""
        shards: Dict[str, Any] = {}
        merged: Dict[str, Any] = {}
        enabled = False
        total = 0
        entry = None
        for sid, ep in self._endpoints.items():
            fn = getattr(ep, "dlq_snapshot", None)
            if not callable(fn):
                continue
            body = fn(topic=topic, id=id)
            shards[sid] = body
            enabled = enabled or bool(body.get("enabled"))
            total += int(body.get("dead_letters_total", 0) or 0)
            if body.get("entry") is not None and entry is None:
                entry = {**body["entry"], "shard": sid}
            for t, info in (body.get("topics") or {}).items():
                agg = merged.setdefault(
                    t, {"count": 0, "pending": 0, "entries": []})
                agg["count"] += int(info.get("count", 0) or 0)
                agg["pending"] += int(info.get("pending", 0) or 0)
                agg["entries"].extend(
                    {**e, "shard": sid} if isinstance(e, dict) else e
                    for e in info.get("entries") or [])
        out = {"enabled": enabled, "sharded": True,
               "dead_letters_total": total, "topics": merged,
               "shards": shards}
        if entry is not None:
            out["entry"] = entry
        return out

    # -- failover / introspection -------------------------------------------
    def shard_outboxes(self) -> List[DurableOutbox]:
        return list(self._outboxes.values())

    def outbox_depth(self) -> int:
        return sum(ob.depth() for ob in self._outboxes.values())

    def drain_outboxes(self, timeout_s: float = 10.0) -> bool:
        import time as _time

        deadline = _time.monotonic() + timeout_s
        ok = True
        for ob in self._outboxes.values():
            left = max(0.1, deadline - _time.monotonic())
            ok = ob.drain(timeout_s=left) and ok
        return ok

    def routed_counts(self, topic: Optional[str] = None
                      ) -> Dict[str, int]:
        """Frames routed per shard (optionally for one topic) — the
        routing-skew read the gate's ``max_shard_skew`` check uses."""
        with self._lock:
            out = {sid: 0 for sid in self._endpoints}
            for (sid, t), n in self._routed_counts.items():
                if topic is None or t == topic:
                    out[sid] += n
        return out

    def generations(self) -> Dict[str, Any]:
        return {sid: getattr(ep, "generation", None)
                for sid, ep in self._endpoints.items()}

    def snapshot(self) -> Dict[str, Any]:
        """The ``/shards`` surface body (tools/watch.py shards panel;
        embedded in postmortem bundles via ``shards_snapshot``)."""
        with self._lock:
            pull_topics = list(self._pull_topics)
            routed = dict(self._routed_counts)
            broadcast = self._broadcast_count
        shards: Dict[str, Any] = {}
        for sid, ep in self._endpoints.items():
            ob = self._outboxes[sid]
            alive: Optional[bool] = None
            if hasattr(ep, "server"):          # BusHandle-shaped
                alive = ep.server is not None
            pending: Dict[str, int] = {}
            fn = getattr(ep, "pending_count", None)
            if callable(fn):
                for t in pull_topics:
                    try:
                        pending[t] = int(fn(t))
                    except Exception as e:
                        logger.debug("pending_count(%s) on %s failed: %s",
                                     t, sid, e)
            shards[sid] = {
                "address": getattr(ep, "address", None)
                or getattr(ep, "target", None),
                "generation": getattr(ep, "generation", None),
                "alive": alive,
                "outbox_depth": ob.depth(),
                "outbox_capacity": ob.cfg.max_frames,
                "breaker": ob.circuit_state,
                "routed_frames": {t: n for (s, t), n in routed.items()
                                  if s == sid},
                "pending": pending,
            }
        return {
            "name": self.name,
            "shards": shards,
            "ring": {"shard_ids": list(self.shard_map.shard_ids),
                     "replicas": self.shard_map.replicas},
            "broadcast_frames": broadcast,
            "pull_topics": pull_topics,
            "outbox_depth_total": sum(
                s["outbox_depth"] for s in shards.values()),
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        for ep in self._endpoints.values():
            fn = getattr(ep, "start", None)
            if callable(fn):
                fn()

    def close(self, drain_s: float = 2.0) -> None:
        for ob in self._outboxes.values():
            ob.close(drain_s=drain_s)
        if not self._close_endpoints:
            return
        for sid, ep in self._endpoints.items():
            fn = getattr(ep, "close", None)
            if callable(fn):
                try:
                    fn()
                except Exception as e:
                    logger.warning("shard %s close error: %s", sid, e)


# --- bench child (`bench.py` bus-throughput leg) ----------------------------

def _bench_child(argv: List[str]) -> int:
    """One shard of the bus-throughput bench: hosts a stock GrpcBusServer
    on a loopback port, publishes this shard's ring-owned slice of a
    seeded uid space through real Publish RPCs, and pulls+acks every
    frame back.  A READY/GO stdin handshake lets the parent start all
    shards' measurement windows together, so the aggregate is a genuine
    concurrent-brokers number (each child is its own OS process — the
    deployment shape, one broker per process)."""
    import argparse
    import json
    import sys
    import time

    p = argparse.ArgumentParser()
    p.add_argument("--bench-child", action="store_true")
    p.add_argument("--shard-index", type=int, required=True)
    p.add_argument("--shard-count", type=int, required=True)
    p.add_argument("--frames", type=int, default=2400)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--payload-bytes", type=int, default=256)
    args = p.parse_args(argv)

    from .grpc_bus import GrpcBusClient, GrpcBusServer
    from .messages import TOPIC_INFERENCE_BATCHES

    sids = default_shard_ids(args.shard_count)
    ring = ShardMap(sids)
    own = sids[args.shard_index]
    uids = [f"post-{args.seed}-{i:06d}" for i in range(args.frames)]
    owned = [u for u in uids if ring.shard_for(u) == own]

    server = GrpcBusServer("127.0.0.1:0")
    server.enable_pull(TOPIC_INFERENCE_BATCHES)
    server.start()
    client = GrpcBusClient(f"127.0.0.1:{server.bound_port}")
    body = "x" * max(0, args.payload_bytes)
    got = 0
    done = threading.Event()

    def _consume() -> None:
        nonlocal got
        for delivery_id, _frame in client.pull(TOPIC_INFERENCE_BATCHES):
            client.ack(TOPIC_INFERENCE_BATCHES, delivery_id, True)
            got += 1
            if got >= len(owned):
                done.set()
                return

    print("READY", flush=True)
    sys.stdin.readline()  # GO — every child starts its window together
    consumer = threading.Thread(target=_consume, daemon=True)
    t0 = time.perf_counter()
    consumer.start()
    for u in owned:
        client.publish(TOPIC_INFERENCE_BATCHES,
                       {"post_uid": u, "batch_id": u, "body": body})
    completed = done.wait(timeout=120.0)
    wall = time.perf_counter() - t0
    print(json.dumps({
        "shard": own, "frames": got, "owned": len(owned),
        "completed": bool(completed), "wall_s": round(wall, 4),
        "frames_per_s": round(got / wall, 1) if wall > 0 else 0.0,
    }), flush=True)
    client.close()
    server.close(grace=0.1)
    return 0 if completed else 1


if __name__ == "__main__":
    import sys as _sys

    if "--bench-child" in _sys.argv:
        _sys.exit(_bench_child(_sys.argv[1:]))
    _sys.stderr.write(
        "usage: python -m distributed_crawler_tpu.bus.partition "
        "--bench-child --shard-index I --shard-count N [--frames F]\n")
    _sys.exit(2)
