"""Distributed crawl worker (reference `worker/`).

The TPU inference worker lives in `inference/worker.py`; this package is the
crawl-side work consumer.
"""

from .worker import CrawlWorker, WorkerConfig, should_retry_error

__all__ = ["CrawlWorker", "WorkerConfig", "should_retry_error"]
