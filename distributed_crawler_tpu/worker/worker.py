"""The crawl worker: consume work items, crawl, report results + heartbeats.

Parity with the reference's `worker/worker.go` (477 LoC):
- subscribe to the work queue, per-item processing with busy/idle status
  transitions (`:164-231`)
- 30 s heartbeat sender (`:234-252`)
- platform dispatch: telegram -> pool-backed crawl engine, youtube -> the
  platform crawler registry (the reference left youtube unimplemented,
  `:403-408`; this build wires it through `crawlers.YouTubeCrawler`)
- retryable-vs-permanent error classification by substring (`:436-456`)
- WorkItemConfig -> CrawlerConfig conversion (`:411-433`)
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..bus.messages import (
    MSG_HEARTBEAT,
    MSG_WORK_ITEM,
    MSG_WORKER_STARTED,
    MSG_WORKER_STOPPING,
    STATUS_ERROR,
    STATUS_SUCCESS,
    TOPIC_RESULTS,
    TOPIC_WORK_QUEUE,
    TOPIC_WORKER_STATUS,
    WORKER_ACTIVE,
    WORKER_BUSY,
    WORKER_IDLE,
    WORKER_OFFLINE,
    DiscoveredPage,
    ResultMessage,
    StatusMessage,
    WorkItem,
    WorkItemConfig,
    WorkQueueMessage,
    WorkResult,
)
from ..config.crawler import CrawlerConfig
from ..crawl import runner as crawl_runner
from ..utils import flight, resilience, trace
from ..utils.slo import SLOWatchdog, standard_slos
from ..utils.telemetry import TelemetryEmitter
from ..state.datamodels import PAGE_PROCESSING, Page, new_id, utcnow

logger = logging.getLogger("dct.worker")

# Error-classification substrings (`worker/worker.go:436-456`).
_PERMANENT_MARKERS = ("not found", "access denied", "forbidden")


def should_retry_error(err: Exception) -> bool:
    """`worker/worker.go:436-456`: permanent markers win; everything else
    (connection/timeout/unknown) defaults to retry."""
    return not any(m in str(err).lower() for m in _PERMANENT_MARKERS)


def work_item_config_to_crawler_config(config: WorkItemConfig,
                                       platform: str) -> CrawlerConfig:
    """`worker/worker.go:411-433`."""
    return CrawlerConfig(
        storage_root=config.storage_root, concurrency=config.concurrency,
        timeout=config.timeout, platform=platform,
        min_post_date=config.min_post_date, post_recency=config.post_recency,
        date_between_min=config.date_between_min,
        date_between_max=config.date_between_max,
        sample_size=config.sample_size, max_comments=config.max_comments,
        max_posts=config.max_posts, max_depth=config.max_depth,
        max_pages=config.max_pages, min_users=config.min_users,
        crawl_label=config.crawl_label,
        skip_media_download=config.skip_media_download,
        youtube_api_key=config.youtube_api_key,
        sampling_method=config.sampling_method or "channel",
        min_channel_videos=config.min_channel_videos)


@dataclass
class WorkerConfig:
    worker_id: str = ""
    heartbeat_s: float = 30.0  # `worker.go:237`
    # SLO budget on the worker.process span's p95 (`utils/slo.py`),
    # evaluated once per heartbeat; 0 = no budget declared.  The crawl
    # worker's unit of work is a crawl item, so this is the crawl-latency
    # twin of the TPU worker's batch budget.
    slo_batch_p95_ms: float = 0.0
    # In-worker fetch attempts per crawl item (utils/resilience.py):
    # transient errors retry locally with backoff — and FLOOD_WAIT-style
    # ``retry_after_s`` hints are honoured as server-directed backoff —
    # before the item is bounced back to the orchestrator's (more
    # expensive) page-level retry loop.  1 disables local retries.
    fetch_attempts: int = 2


class CrawlWorker:
    """Work consumer (`worker/worker.go:28-96`)."""

    def __init__(self, worker_id: str, config: CrawlerConfig, bus, sm,
                 wcfg: Optional[WorkerConfig] = None,
                 youtube_crawler=None):
        if not worker_id:
            raise ValueError("worker ID cannot be empty")
        self.id = worker_id
        self.config = config
        self.bus = bus
        self.sm = sm
        self.wcfg = wcfg or WorkerConfig(worker_id=worker_id)
        self.youtube_crawler = youtube_crawler

        self.tasks_processed = 0
        self.tasks_success = 0
        self.tasks_error = 0
        self.current_work: Optional[WorkItem] = None
        # Telemetry-rich heartbeats (RSS, latency digest; device stats only
        # if this process already runs jax — the emitter never imports it).
        self._telemetry = TelemetryEmitter()
        # SLO watchdog over worker.process p95; empty with no budget.
        self._slo = SLOWatchdog(standard_slos(
            batch_p95_ms=self.wcfg.slo_batch_p95_ms))
        # Crawl fetches run under the shared resiliency policy: only
        # errors `should_retry_error` classifies as transient are
        # retried; permanent failures go straight back as an error
        # result.
        self._fetch_policy = resilience.Policy(
            op="crawl.fetch",
            retry=resilience.RetryPolicy(
                max_attempts=max(1, self.wcfg.fetch_attempts),
                base_delay_s=0.2, max_delay_s=5.0,
                retryable=should_retry_error))
        self._mu = threading.RLock()
        self._running = False
        self._threads: List[threading.Thread] = []
        self._started_at = time.monotonic()

    # -- lifecycle (`worker.go:96-160`) ------------------------------------
    def start(self, background: bool = True) -> None:
        with self._mu:
            if self._running:
                raise RuntimeError("worker is already running")
            self._running = True
        self._started_at = time.monotonic()
        self.bus.subscribe(TOPIC_WORK_QUEUE, self.handle_work_payload)
        if background:
            t = threading.Thread(target=self._heartbeat_loop, daemon=True,
                                 name=f"worker-heartbeat-{self.id}")
            t.start()
            self._threads.append(t)
        self.send_status_update(MSG_WORKER_STARTED, WORKER_ACTIVE,
                                telemetry=True)
        logger.info("worker started", extra={"worker_id": self.id})

    def stop(self) -> None:
        with self._mu:
            self._running = False
        self.send_status_update(MSG_WORKER_STOPPING, WORKER_OFFLINE)
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
        self.sm.close()
        logger.info("worker stopped", extra={"worker_id": self.id})

    def kill(self) -> None:
        """Abrupt-death simulation (the chaos/`loadgen` seam): stop the
        heartbeat loop WITHOUT the stopping status message or the state-
        manager close — the in-process analog of SIGKILL.  The orchestrator
        discovers the death the production way: heartbeats go silent until
        `check_worker_health` marks the worker offline and reassigns its
        in-flight items."""
        with self._mu:
            self._running = False
        flight.record("worker_kill", worker=self.id,
                      current_work=(self.current_work.id
                                    if self.current_work else None))
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()

    def evaluate_slos(self) -> list:
        """One on-demand SLO tick (the heartbeat loop's twin) — see
        `TPUWorker.evaluate_slos`."""
        return self._slo.evaluate()

    @property
    def is_running(self) -> bool:
        with self._mu:
            return self._running

    # -- heartbeats (`worker.go:234-252`) ----------------------------------
    def _heartbeat_loop(self) -> None:
        while self.is_running:
            deadline = time.monotonic() + self.wcfg.heartbeat_s
            while self.is_running and time.monotonic() < deadline:
                time.sleep(0.05)
            if not self.is_running:
                return
            try:
                # SLO tick: spans completed since the last beat vs the
                # declared budget (no-op without one).
                self._slo.evaluate()
            except Exception as e:
                logger.warning("slo evaluation failed: %s", e)
            self.send_status_update(MSG_HEARTBEAT, self.determine_status(),
                                    telemetry=True)

    def determine_status(self) -> str:
        if not self.is_running:
            return WORKER_OFFLINE
        with self._mu:
            return WORKER_BUSY if self.current_work is not None else WORKER_IDLE

    def send_status_update(self, message_type: str, status: str,
                           telemetry: bool = False) -> None:
        """`worker.go:255-295`.  ``telemetry=True`` (the interval
        heartbeat and the started announcement) attaches the
        `utils/telemetry.py` snapshot; per-item busy/idle transitions
        stay light — snapshotting there would both pay an O(trace-ring)
        digest per work item and reset the digest window the interval
        beat is supposed to cover."""
        with self._mu:
            current = self.current_work.id if self.current_work else None
        msg = StatusMessage.new(
            self.id, message_type, status,
            tasks_processed=self.tasks_processed,
            tasks_success=self.tasks_success, tasks_error=self.tasks_error,
            uptime_s=time.monotonic() - self._started_at)
        msg.current_work = current
        if telemetry:
            msg.resource_usage = self._telemetry.snapshot()
            # Cumulative breach counts for the watchtower's burn-rate
            # fold (the serving workers' discipline).
            msg.resource_usage["slo_breaches"] = \
                self._slo.snapshot()["breaches"]
        try:
            self.bus.publish(TOPIC_WORKER_STATUS, msg)
        except Exception as e:
            logger.error("failed to send status update", extra={
                "message_type": message_type, "error": str(e)})

    # -- work handling (`worker.go:164-231`) -------------------------------
    def handle_work_payload(self, payload: Dict[str, Any]) -> None:
        self.handle_work_message(WorkQueueMessage.from_dict(payload))

    def handle_work_message(self, message: WorkQueueMessage) -> None:
        if message.message_type != MSG_WORK_ITEM:
            logger.debug("ignoring non-work message",
                         extra={"message_type": message.message_type})
            return
        if message.expired():
            logger.warning("dropping expired work item", extra={
                "work_item_id": message.work_item.id})
            return
        item = message.work_item
        with self._mu:
            self.current_work = item
        start = time.monotonic()
        flight.record("work_start", work_item=item.id, worker=self.id,
                      url=item.url)
        self.send_status_update(MSG_HEARTBEAT, WORKER_BUSY)
        try:
            # Same trace as the orchestrator's dispatch span: the item
            # carried its trace_id across the bus hop.
            with trace.span("worker.process", trace_id=item.trace_id,
                            work_item=item.id, worker=self.id,
                            platform=item.platform) as sp:
                result = self.process_work_item(item)
                sp.set(status=result.status,
                       message_count=result.message_count)
        finally:
            with self._mu:
                self.current_work = None
        try:
            with trace.span("worker.publish_result", trace_id=item.trace_id,
                            work_item=item.id, status=result.status):
                self.bus.publish(TOPIC_RESULTS,
                                 ResultMessage.new(result,
                                                   result.discovered_pages,
                                                   trace_id=item.trace_id))
        except Exception as e:
            # Re-raise so the bus redelivers the work item (the reference
            # returns the error for pubsub retry, `worker.go:210-214`).
            logger.error("failed to publish result", extra={
                "work_item_id": item.id, "error": str(e)})
            raise
        # Counters move only after a successful publish so a redelivered
        # item doesn't double-count.
        with self._mu:
            if result.status == STATUS_SUCCESS:
                self.tasks_success += 1
            else:
                self.tasks_error += 1
            self.tasks_processed += 1
        flight.record("work_done", work_item=item.id, worker=self.id,
                      status=result.status, error=result.error or None)
        self.send_status_update(MSG_HEARTBEAT, WORKER_IDLE)
        logger.info("work item processed and result sent", extra={
            "work_item_id": item.id, "status": result.status,
            "processing_time_s": time.monotonic() - start})

    # -- processing (`worker.go:302-408`) ----------------------------------
    def process_work_item(self, item: WorkItem) -> WorkResult:
        start = time.monotonic()
        page = Page(id=item.parent_id or new_id(), url=item.url,
                    depth=item.depth, status=PAGE_PROCESSING,
                    timestamp=utcnow(), parent_id=item.parent_id)
        discovered: List[Page] = []
        message_count = 0
        item_errors: List[str] = []
        error: Optional[Exception] = None
        try:
            if item.platform == "telegram":
                discovered = self._process_telegram(page, item)
                message_count = sum(1 for m in page.messages
                                    if m.status == "fetched")
            elif item.platform == "youtube":
                discovered, message_count, item_errors = \
                    self._process_youtube(page, item)
            else:
                raise ValueError(f"unsupported platform: {item.platform}")
        except Exception as e:
            error = e
            logger.error("failed to process work item", extra={
                "work_item_id": item.id, "error": str(e)})

        result = WorkResult(
            work_item_id=item.id, worker_id=self.id, processed_url=item.url,
            message_count=message_count,
            processing_time_s=time.monotonic() - start,
            completed_at=utcnow(),
            metadata={"platform": item.platform, "depth": item.depth})
        if item_errors:
            result.metadata["item_errors"] = item_errors
        if error is not None:
            result.status = STATUS_ERROR
            result.error = str(error)
            result.retry_recommended = should_retry_error(error)
        else:
            result.status = STATUS_SUCCESS
            result.discovered_pages = [
                DiscoveredPage(url=p.url, parent_id=p.parent_id,
                               depth=p.depth, platform=item.platform)
                for p in discovered]
        return result

    def _process_telegram(self, page: Page, item: WorkItem) -> List[Page]:
        """`worker.go:384-401`: pool-backed crawl engine run, behind the
        fetch resiliency policy."""
        cfg = work_item_config_to_crawler_config(item.config, "telegram")
        cfg.crawl_id = item.crawl_id or self.config.crawl_id
        return self._fetch_policy.call(
            crawl_runner.run_for_channel_with_pool,
            page, item.config.storage_root, self.sm, cfg)

    def _process_youtube(self, page: Page, item: WorkItem
                         ) -> "tuple[List[Page], int, List[str]]":
        """YouTube in distributed mode — implemented here via the crawler
        registry (the reference returned 'not yet implemented',
        `worker.go:403-408`).  Returns (discovered, post_count, errors)."""
        if self.youtube_crawler is None:
            raise ValueError(
                "YouTube processing requires a youtube_crawler instance")
        from ..crawlers.base import CrawlJob, CrawlTarget
        cfg = item.config
        job = CrawlJob(
            target=CrawlTarget(id=item.url, type="youtube"),
            from_time=cfg.min_post_date or cfg.date_between_min,
            to_time=cfg.date_between_max,
            limit=cfg.max_posts if cfg.max_posts > 0 else 0,
            sample_size=cfg.sample_size)
        result = self.youtube_crawler.fetch_messages(job)
        discovered: List[Page] = []
        seen = {item.url}
        for post in result.posts:
            for link in post.outlinks:
                if link not in seen:
                    seen.add(link)
                    discovered.append(Page(
                        id=new_id(), url=link, depth=page.depth + 1,
                        parent_id=page.id))
        return discovered, len(result.posts), list(result.errors)

    # -- status (`worker.go:459-477`) --------------------------------------
    def get_status(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "worker_id": self.id,
                "is_running": self._running,
                "platform": self.config.platform,
                "tasks_processed": self.tasks_processed,
                "tasks_success": self.tasks_success,
                "tasks_error": self.tasks_error,
                "uptime_seconds": time.monotonic() - self._started_at,
            }
