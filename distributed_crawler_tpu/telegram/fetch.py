"""Paged history fetching with date windows and sampling.

Parity with `telegramhelper/telegramutils.go`:
- `fetch_channel_messages_with_sampling`: 100-message pages walked newest to
  oldest with min/max date windows, early termination, stall detection, and
  Fisher-Yates sampling (`:25-157`)
- member counts (`:159-310`) and comment-thread fetching (`:311`).
"""

from __future__ import annotations

import logging
import random
from datetime import datetime
from typing import List, Optional

from ..clients.telegram import TelegramClient, TLMessage
from ..state.datamodels import Page

logger = logging.getLogger("dct.telegram.fetch")

PAGE_SIZE = 100  # messages per history page (`telegramutils.go:49`)


def fetch_channel_messages_with_sampling(
        client: TelegramClient, chat_id: int, page: Page,
        min_post_date: Optional[datetime] = None,
        max_post_date: Optional[datetime] = None,
        max_posts: int = -1, sample_size: int = 0,
        rng: Optional[random.Random] = None) -> List[TLMessage]:
    """`telegramutils.go:25-157`."""
    all_messages: List[TLMessage] = []
    from_message_id = 0
    oldest_message_id = 0
    first_batch = True
    min_unix = int(min_post_date.timestamp()) if min_post_date else None
    max_unix = int(max_post_date.timestamp()) if max_post_date else None

    while True:
        history = client.get_chat_history(chat_id,
                                          from_message_id=from_message_id,
                                          limit=PAGE_SIZE)
        if not history.messages:
            break
        if first_batch:
            public_msg_id = history.messages[0].id // 1048576
            logger.info("estimated post count for channel", extra={
                "channel": page.url, "total_posts": public_msg_id})
            first_batch = False

        reached_old = False
        for msg in history.messages:
            if min_unix is not None and msg.date < min_unix:
                reached_old = True
                break
            if max_unix is not None and msg.date > max_unix:
                continue  # newer than the window: skip, keep walking older
            all_messages.append(msg)
            if 0 <= max_posts == len(all_messages):
                reached_old = True
                break
        if reached_old:
            break

        last_message_id = history.messages[-1].id
        if last_message_id == oldest_message_id:
            break  # stalled: same oldest message as the previous page
        oldest_message_id = last_message_id
        from_message_id = last_message_id

    logger.debug("fetched %d messages for %s", len(all_messages), page.url)

    # Fisher-Yates sample when requested (`telegramutils.go:124-154`).
    if 0 < sample_size < len(all_messages):
        rng = rng or random.Random()
        sampled = list(all_messages)
        rng.shuffle(sampled)
        sampled = sampled[:sample_size]
        logger.info("random sampling applied", extra={
            "channel": page.url, "original": len(all_messages),
            "sampled": len(sampled)})
        return sampled
    return all_messages


def get_channel_member_count(client: TelegramClient, username: str) -> int:
    """Member count via chat -> supergroup full info
    (`telegramutils.go:159-310`)."""
    chat = client.search_public_chat(username)
    if chat.supergroup_id:
        try:
            info = client.get_supergroup_full_info(chat.supergroup_id)
            if info.member_count:
                return info.member_count
        except Exception as e:
            logger.debug("full-info member count unavailable; falling "
                         "back to get_supergroup", extra={
                             "username": username, "error": str(e)})
        sg = client.get_supergroup(chat.supergroup_id)
        return sg.member_count
    return 0


def get_message_comments(client: TelegramClient, chat_id: int, message_id: int,
                         max_comments: int = 100) -> List[TLMessage]:
    """Comment thread of a post (`telegramutils.go:311`)."""
    try:
        thread = client.get_message_thread_history(
            chat_id, message_id,
            limit=max_comments if max_comments > 0 else 100)
        return thread.messages
    except Exception as e:
        logger.debug("no comment thread", extra={
            "chat_id": chat_id, "message_id": message_id, "error": str(e)})
        return []
