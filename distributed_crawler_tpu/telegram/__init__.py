"""Telegram message parsing + fetch utilities.

Parity with the reference's `telegramhelper/` parsing/fetch layer
(`tdutils.go`, `telegramutils.go`): message -> Post conversion across content
types, media fetch with dedup + size cap, channel-link extraction with source
attribution, paged history walks with date windows and sampling.
"""

from .fetch import (
    fetch_channel_messages_with_sampling,
    get_channel_member_count,
    get_message_comments,
)
from .parsing import (
    SOURCE_MENTION,
    SOURCE_PLAINTEXT,
    SOURCE_TEXT_URL,
    SOURCE_URL,
    DiscoveredLink,
    build_telegram_link,
    extract_channel_links,
    extract_channel_links_with_source,
    fetch_and_upload_media,
    parse_message,
    utf16_slice,
)

__all__ = [
    "parse_message",
    "fetch_and_upload_media",
    "extract_channel_links",
    "extract_channel_links_with_source",
    "DiscoveredLink",
    "build_telegram_link",
    "utf16_slice",
    "SOURCE_MENTION",
    "SOURCE_TEXT_URL",
    "SOURCE_URL",
    "SOURCE_PLAINTEXT",
    "fetch_channel_messages_with_sampling",
    "get_channel_member_count",
    "get_message_comments",
]
