"""Message -> Post parsing, link extraction, media handling.

Parity with the reference's `telegramhelper/tdutils.go`:
- `parse_message`: message -> 75-field Post across 12+ content types with the
  caller providing panic containment (`tdutils.go:380-720`)
- media fetch/upload: 150 MB cap, dedup via the media cache, client-side file
  deletion after upload (`tdutils.go:226-358,780-896`)
- UTF-16 entity offset handling (`tdutils.go:55`)
- channel-link extraction with source-type attribution
  (mention/text_url/url/plaintext, `tdutils.go:897-1003`)
- public t.me link building: message ID / 1048576 (`tdutils.go:1005-1008`)

Message content is the tagged-dict union produced by the client boundary
(`clients/telegram.py` TLMessage.content).
"""

from __future__ import annotations

import logging
import os
import re
from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional

from ..config.crawler import CrawlerConfig
from ..datamodel import ChannelData, Comment, EngagementData, MediaData, Post
from ..clients.telegram import TelegramClient, TLChat, TLMessage, TLSupergroup, TLSupergroupFullInfo

logger = logging.getLogger("dct.telegram.parse")

MAX_MEDIA_BYTES = 150 * 1048576  # 150 MB cap (`tdutils.go:284-293`)

# Source types, most to least reliable (`tdutils.go:93-96`).
SOURCE_MENTION = "mention"
SOURCE_TEXT_URL = "text_url"
SOURCE_URL = "url"
SOURCE_PLAINTEXT = "plaintext"

_TME_RE = re.compile(r"t\.me/([A-Za-z0-9_]{3,32})")
_AT_RE = re.compile(r"@([A-Za-z][A-Za-z0-9_]{3,31})")

# t.me paths that are features, not channels.
_RESERVED = {"joinchat", "addstickers", "addtheme", "addlist", "share", "proxy",
             "socks", "iv", "c", "s", "bg", "login", "invoice", "setlanguage",
             "confirmphone", "contact", "addemoji", "boost"}


@dataclass
class DiscoveredLink:
    """A channel username + how it was extracted (`tdutils.go:85-96`)."""

    name: str
    source_type: str


def utf16_slice(s: str, utf16_offset: int, utf16_length: int) -> str:
    """Slice a Python string by TDLib's UTF-16 code-unit offsets
    (`tdutils.go:55-83`)."""
    units = 0
    start = end = len(s)
    target_end = utf16_offset + utf16_length
    for i, ch in enumerate(s):
        if units >= utf16_offset and start == len(s):
            start = i
        if units >= target_end:
            end = i
            break
        units += 2 if ord(ch) > 0xFFFF else 1
    else:
        if units >= utf16_offset and start == len(s):
            start = len(s)
        end = len(s)
    return s[start:end]


def build_telegram_link(username: str, message_id: int) -> str:
    """Public post link; TDLib internal ID >> 20 (`tdutils.go:1005-1008`)."""
    return f"https://t.me/{username}/{message_id // 1048576}"


def _clean_username(raw: str) -> Optional[str]:
    name = raw.strip().strip("/").lower()
    if name.startswith("@"):
        name = name[1:]
    if not name or name in _RESERVED:
        return None
    return name


def _extract_formatted_text(content: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The formatted-text node per content type (`tdutils.go:953-973`)."""
    ctype = content.get("@type", "")
    if ctype == "messageText":
        return content.get("text")
    if ctype in ("messagePhoto", "messageVideo", "messageDocument",
                 "messageAnimation", "messageAudio", "messageVoiceNote",
                 "messagePaidMedia"):
        return content.get("caption")
    return None


def _links_from_formatted_text(ft: Dict[str, Any],
                               source_map: Dict[str, str]) -> None:
    """Walk entities most-reliable-first, then plaintext scan
    (`tdutils.go:897-951`)."""
    text = ft.get("text", "") or ""

    def add_if_new(name: Optional[str], source: str) -> None:
        if name and name not in source_map:
            source_map[name] = source

    # Reliability order among entity types: mention > text_url > url.  The
    # pass order (not in-message order) decides attribution, so a username
    # seen both as a bare URL and an @mention is credited to the mention.
    _RELIABILITY = ("textEntityTypeMention", "textEntityTypeTextUrl",
                    "textEntityTypeUrl")
    entities = ft.get("entities") or []
    for wanted in _RELIABILITY:
        for entity in entities:
            etype = (entity.get("type") or {}).get("@type", "")
            if etype != wanted:
                continue
            if etype == "textEntityTypeTextUrl":
                url = (entity.get("type") or {}).get("url", "")
                m = _TME_RE.search(url)
                if m:
                    add_if_new(_clean_username(m.group(1)), SOURCE_TEXT_URL)
            elif etype == "textEntityTypeMention":
                mention = utf16_slice(text, int(entity.get("offset", 0)),
                                      int(entity.get("length", 0)))
                add_if_new(_clean_username(mention), SOURCE_MENTION)
            else:  # textEntityTypeUrl
                url = utf16_slice(text, int(entity.get("offset", 0)),
                                  int(entity.get("length", 0)))
                m = _TME_RE.search(url)
                if m:
                    add_if_new(_clean_username(m.group(1)), SOURCE_URL)

    # Plain-text scan, least reliable.
    for m in _TME_RE.finditer(text):
        add_if_new(_clean_username(m.group(1)), SOURCE_PLAINTEXT)
    for m in _AT_RE.finditer(text):
        add_if_new(_clean_username(m.group(1)), SOURCE_PLAINTEXT)


def extract_channel_links_with_source(message: TLMessage) -> List[DiscoveredLink]:
    """All channel usernames referenced by a message, with attribution
    (`tdutils.go:978-987`)."""
    source_map: Dict[str, str] = {}
    ft = _extract_formatted_text(message.content)
    if ft:
        _links_from_formatted_text(ft, source_map)
    return [DiscoveredLink(name=n, source_type=s) for n, s in source_map.items()]


def extract_channel_links(message: TLMessage) -> List[str]:
    """`tdutils.go:989-1003`."""
    return [l.name for l in extract_channel_links_with_source(message)]


def fetch_and_upload_media(client: TelegramClient, sm, crawl_id: str,
                           channel_name: str, remote_file_id: str,
                           post_link: str, cfg: CrawlerConfig) -> str:
    """Download a media file and hand it to the state provider
    (`tdutils.go:226-358,780-896`).

    Returns the stored file name ("" when skipped).  Dedup through the media
    cache; size cap 150 MB; the client-side copy is deleted after upload.
    """
    if cfg.skip_media_download or not remote_file_id:
        return ""
    if sm.has_processed_media(remote_file_id):
        logger.debug("media already processed", extra={"media_id": remote_file_id})
        return ""
    try:
        handle = client.get_remote_file(remote_file_id)
        if handle.size > MAX_MEDIA_BYTES:
            logger.info("media exceeds size cap, skipping",
                        extra={"media_id": remote_file_id, "size": handle.size})
            sm.mark_media_as_processed(remote_file_id)
            return ""
        downloaded = client.download_file(handle.id)
        if not downloaded.local_path:
            return ""
        file_name = os.path.basename(downloaded.local_path)
        stored_path, stored_name = sm.store_file(channel_name,
                                                 downloaded.local_path, file_name)
        # Media -> ASR seam (`media/bridge.py:MediaBridge`): a bridged
        # manager publishes the stored ref to the media topic so the ASR
        # worker transcribes it; plain managers don't implement the hook.
        # Notify BEFORE the cache mark: once marked, a re-crawl never
        # re-fetches this media, so a crash between the two would
        # otherwise lose the transcript forever — duplicate notifies
        # from a mark-less retry are absorbed by the bridge's dedupe
        # window.
        notify = getattr(sm, "notify_media_stored", None)
        if callable(notify):
            notify(media_id=remote_file_id, path=stored_path,
                   channel_name=channel_name)
        sm.mark_media_as_processed(remote_file_id)
        # Free TDLib-side disk (`tdutils.go` DeleteFile usage).
        try:
            client.delete_file(downloaded.id)
        except Exception:
            pass
        return stored_name
    except Exception as e:
        logger.warning("media fetch failed", extra={
            "media_id": remote_file_id, "post_link": post_link, "error": str(e)})
        return ""


_CONTENT_TEXT_KEYS = {
    "messageText": ("text", "text"),
    "messagePhoto": ("caption", "text"),
    "messageVideo": ("caption", "text"),
    "messageDocument": ("caption", "text"),
    "messageAnimation": ("caption", "text"),
    "messageAudio": ("caption", "text"),
    "messageVoiceNote": ("caption", "text"),
    "messagePaidMedia": ("caption", "text"),
}


def _content_text(content: Dict[str, Any]) -> str:
    ctype = content.get("@type", "")
    keys = _CONTENT_TEXT_KEYS.get(ctype)
    if keys:
        node = content.get(keys[0]) or {}
        return node.get(keys[1], "") or ""
    if ctype == "messagePoll":
        poll = content.get("poll") or {}
        q = (poll.get("question") or {})
        question = q.get("text", "") if isinstance(q, dict) else str(q)
        options = []
        for opt in poll.get("options") or []:
            t = opt.get("text")
            options.append(t.get("text", "") if isinstance(t, dict) else str(t))
        return "\n".join([question] + options)
    if ctype == "messageAnimatedEmoji":
        return content.get("emoji", "") or ""
    if ctype == "messageSticker":
        return (content.get("sticker") or {}).get("emoji", "") or ""
    if ctype in ("messageGiveaway", "messageGiveawayWinners",
                 "messageGiveawayCompleted"):
        return content.get("description", "") or ""
    return ""


def _media_remote_id(content: Dict[str, Any]) -> str:
    """Remote file ID of the primary media object, if any."""
    ctype = content.get("@type", "")
    for key in ("video", "photo", "animation", "document", "audio",
                "voice_note", "video_note", "sticker"):
        node = content.get(key)
        if isinstance(node, dict):
            rid = node.get("remote_id", "")
            if rid:
                return rid
    if ctype == "messagePhoto":
        sizes = (content.get("photo") or {}).get("sizes") or []
        if sizes:
            return sizes[-1].get("remote_id", "")
    return ""


def _post_type(content: Dict[str, Any]) -> List[str]:
    ctype = content.get("@type", "messageText")
    mapping = {
        "messageText": "text", "messagePhoto": "image", "messageVideo": "video",
        "messageAnimation": "video", "messageVideoNote": "video",
        "messageAudio": "audio", "messageVoiceNote": "audio",
        "messageDocument": "document", "messageSticker": "sticker",
        "messagePoll": "poll", "messageAnimatedEmoji": "text",
        "messageGiveaway": "giveaway", "messageGiveawayWinners": "giveaway",
        "messageGiveawayCompleted": "giveaway", "messagePaidMedia": "paid_media",
    }
    return [mapping.get(ctype, "other")]


def parse_message(crawl_id: str, message: TLMessage, chat: TLChat,
                  supergroup: Optional[TLSupergroup],
                  supergroup_info: Optional[TLSupergroupFullInfo],
                  message_count: int, total_views: int, channel_username: str,
                  client: TelegramClient, sm, cfg: CrawlerConfig) -> Post:
    """Convert one message into the canonical Post (`tdutils.go:380-720`).

    Raises on malformed content; the caller wraps with recovery so one bad
    message never kills a channel (`crawl/runner.go:1720-1809`).
    """
    content = message.content or {}
    text = _content_text(content)
    post_link = build_telegram_link(channel_username, message.id)
    published = datetime.fromtimestamp(message.date, tz=timezone.utc) \
        if message.date else None

    # Media (respecting cap/dedup/skip config).
    document_name = ""
    remote_id = _media_remote_id(content)
    if remote_id:
        document_name = fetch_and_upload_media(
            client, sm, crawl_id, channel_username, remote_id, post_link, cfg)

    # Comments (`telegramutils.go:311`): only when the post has replies.
    comments: List[Comment] = []
    if message.reply_count > 0 and cfg.max_comments != 0:
        try:
            thread = client.get_message_thread_history(
                message.chat_id, message.id,
                limit=cfg.max_comments if cfg.max_comments > 0 else 100)
            for cm in thread.messages:
                comments.append(Comment(
                    text=_content_text(cm.content or {}),
                    reactions=dict(cm.reactions or {}),
                    view_count=cm.view_count,
                    reply_count=cm.reply_count,
                    handle=cm.sender_username,
                ))
        except Exception as e:
            logger.debug("comment fetch failed", extra={
                "post_link": post_link, "error": str(e)})

    outlinks = extract_channel_links(message)
    description = (supergroup_info.description if supergroup_info else "") or ""
    member_count = supergroup_info.member_count if supergroup_info else (
        supergroup.member_count if supergroup else 0)

    engagement = message.view_count + message.forward_count + message.reply_count
    post = Post(
        post_link=post_link,
        channel_id=str(chat.id),
        post_uid=f"{chat.id}_{message.id}",
        url=post_link,
        published_at=published,
        created_at=published,
        engagement=engagement,
        view_count=message.view_count,
        share_count=message.forward_count,
        comment_count=message.reply_count,
        crawl_label=cfg.crawl_label,
        channel_name=chat.title,
        channel_data=ChannelData(
            channel_id=str(chat.id),
            channel_name=chat.title,
            channel_description=description,
            channel_engagement_data=EngagementData(
                follower_count=member_count,
                post_count=message_count,
                views_count=total_views,
            ),
            channel_url_external=f"https://t.me/{channel_username}",
            channel_url=f"https://t.me/{channel_username}",
        ),
        platform_name="telegram",
        description=text,
        post_type=_post_type(content),
        media_data=MediaData(document_name=document_name),
        shares_count=message.forward_count,
        comments_count=message.reply_count,
        views_count=message.view_count,
        comments=comments,
        reactions=dict(message.reactions or {}),
        outlinks=outlinks,
        capture_time=datetime.now(timezone.utc),
        handle=channel_username,
    )
    return post
