-- ============================================================================
-- Random-walk graph + tandem validator schema (sqlite-compatible DDL).
--
-- Table/column parity with the reference's PostgreSQL schemas
-- (`sql/random-walk-schema.sql`, `sql/validator-schema.sql`); the TPU build
-- runs these in-tree (sqlite by default, any DB-API engine via SqlBinding).
-- Timestamps are ISO-8601 TEXT supplied by the application so the SQL is
-- engine-neutral.
-- ============================================================================

-- One row per (source -> destination) edge observation; duplicates intended.
CREATE TABLE IF NOT EXISTS edge_records (
    edge_id             INTEGER PRIMARY KEY AUTOINCREMENT,
    destination_channel TEXT    NOT NULL,
    source_channel      TEXT    NOT NULL,
    walkback            INTEGER NOT NULL,
    skipped             INTEGER NOT NULL,
    discovery_time      TEXT    NOT NULL,
    crawl_id            TEXT    NOT NULL,
    -- UUID shared across all edges in one uninterrupted forward chain;
    -- a walkback starts a fresh chain (empty = tracking unused).
    sequence_id         TEXT    NOT NULL DEFAULT ''
);
CREATE INDEX IF NOT EXISTS idx_edge_records_crawl_id ON edge_records (crawl_id);
CREATE INDEX IF NOT EXISTS idx_edge_records_source_channel ON edge_records (source_channel);
CREATE INDEX IF NOT EXISTS idx_edge_records_sequence_id ON edge_records (sequence_id)
    WHERE sequence_id <> '';
CREATE INDEX IF NOT EXISTS idx_edge_records_discovery_time ON edge_records (discovery_time);
CREATE INDEX IF NOT EXISTS idx_edge_records_crawl_source ON edge_records (crawl_id, source_channel);

-- Transient queue of pages for the next BFS/random-walk step (pod-scoped by crawl_id).
CREATE TABLE IF NOT EXISTS page_buffer (
    page_id     TEXT PRIMARY KEY,
    parent_id   TEXT NOT NULL,
    depth       INTEGER NOT NULL,
    url         TEXT NOT NULL,
    crawl_id    TEXT NOT NULL,
    sequence_id TEXT NOT NULL DEFAULT ''
);
CREATE INDEX IF NOT EXISTS idx_page_buffer_crawl_id ON page_buffer (crawl_id);

-- Seed pool + chat-ID cache + last-crawl watermark.
CREATE TABLE IF NOT EXISTS seed_channels (
    channel_username TEXT PRIMARY KEY,
    chat_id          INTEGER,
    last_crawled_at  TEXT,
    invalidated_at   TEXT,
    member_count     INTEGER,
    inserted_at      TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_seed_channels_last_crawled ON seed_channels (last_crawled_at);
CREATE INDEX IF NOT EXISTS idx_seed_channels_uncrawled ON seed_channels (inserted_at)
    WHERE last_crawled_at IS NULL;

-- Shared cache of usernames that failed validation (30-day TTL in app logic).
CREATE TABLE IF NOT EXISTS invalid_channels (
    channel_username TEXT PRIMARY KEY,
    reason           TEXT NOT NULL DEFAULT '',
    invalidated_at   TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_invalid_channels_invalidated_at ON invalid_channels (invalidated_at);

-- One row per source channel crawled in tandem mode.
-- status: open -> closed -> processing -> completed
CREATE TABLE IF NOT EXISTS pending_edge_batches (
    batch_id       TEXT PRIMARY KEY,
    crawl_id       TEXT NOT NULL,
    source_channel TEXT NOT NULL,
    source_page_id TEXT NOT NULL,
    source_depth   INTEGER NOT NULL,
    sequence_id    TEXT NOT NULL DEFAULT '',
    status         TEXT NOT NULL DEFAULT 'open',
    attempt_count  INTEGER NOT NULL DEFAULT 0,
    created_at     TEXT NOT NULL,
    closed_at      TEXT,
    claimed_at     TEXT,
    completed_at   TEXT
);
CREATE INDEX IF NOT EXISTS idx_pending_batches_status ON pending_edge_batches (status, created_at);
CREATE INDEX IF NOT EXISTS idx_pending_batches_crawl_incomplete ON pending_edge_batches (crawl_id)
    WHERE status <> 'completed';

-- One row per extracted username, streamed by the crawler; claimed by validators.
CREATE TABLE IF NOT EXISTS pending_edges (
    pending_id          INTEGER PRIMARY KEY AUTOINCREMENT,
    batch_id            TEXT NOT NULL REFERENCES pending_edge_batches(batch_id),
    crawl_id            TEXT NOT NULL,
    destination_channel TEXT NOT NULL,
    source_channel      TEXT NOT NULL,
    sequence_id         TEXT NOT NULL DEFAULT '',
    discovery_time      TEXT NOT NULL,
    source_type         TEXT NOT NULL DEFAULT '',
    validation_status   TEXT NOT NULL DEFAULT 'pending',
    validation_reason   TEXT NOT NULL DEFAULT '',
    validated_at        TEXT
);
CREATE INDEX IF NOT EXISTS idx_pending_edges_batch ON pending_edges (batch_id);
CREATE INDEX IF NOT EXISTS idx_pending_edges_pending ON pending_edges (discovery_time)
    WHERE validation_status = 'pending';

-- Aggregated hit/miss counts per extraction source type, per crawl.
CREATE TABLE IF NOT EXISTS source_type_stats (
    crawl_id    TEXT NOT NULL,
    source_type TEXT NOT NULL,
    total       INTEGER NOT NULL DEFAULT 0,
    valid       INTEGER NOT NULL DEFAULT 0,
    not_channel INTEGER NOT NULL DEFAULT 0,
    invalid     INTEGER NOT NULL DEFAULT 0,
    duplicate   INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (crawl_id, source_type)
);

-- DB-backed first-discovery dedup: PK serializes concurrent claims so
-- exactly one crawl wins per channel across history.
CREATE TABLE IF NOT EXISTS discovered_channels (
    channel_username TEXT NOT NULL,
    crawl_id         TEXT NOT NULL,
    discovered_at    TEXT NOT NULL,
    PRIMARY KEY (channel_username)
);

-- Append-only log of validator-detected IP blocks; an external process polls
-- this to trigger IP rotation.
CREATE TABLE IF NOT EXISTS access_events (
    id          INTEGER PRIMARY KEY AUTOINCREMENT,
    reason      TEXT NOT NULL,
    occurred_at TEXT NOT NULL
);
