"""distributed_crawler_tpu — a TPU-native distributed crawler + inference framework.

A ground-up rebuild of the capabilities of researchaccelerator-hub/distributed-crawler
(a Go/Dapr distributed social-media crawler; see SURVEY.md) re-designed TPU-first:

- crawl engine, random-walk/snowball/random sampling, tandem crawler/validator
  pipeline, orchestrator/worker fan-out, pluggable state backends (Python, with a
  C++ native client boundary where the reference used TDLib/C++);
- an in-tree TPU inference stage (JAX/Flax/pjit over a device mesh): multilingual
  embedding (E5 family), content classification (XLM-R family) and ASR (Whisper
  family), fed by a record-batching message bus.

Package layout:
  datamodel/   canonical Post/ChannelData schema + null-validation
  config/      crawler + distributed config, precedence chain
  state/       state-management interface, local/SQL providers, media cache
  bus/         typed message envelopes, record-batch codec, in-memory + gRPC bus
  clients/     TDLib-class client boundary, pools, rate limiters, YouTube client
  crawl/       crawl engine (runner, walkback, tandem, validator)
  crawlers/    platform crawler registry (telegram, youtube)
  orchestrator/, worker/   distributed coordination
  models/      Flax model families (E5, XLM-R, Whisper)
  ops/         Pallas TPU kernels
  parallel/    mesh/sharding/ring-attention (ICI-first collectives)
  inference/   TPU inference worker (tokenize -> bucket -> pjit step)
  modes/       execution modes (standalone, layerless, jobs, distributed)
  chunk/       file-combining pipeline
  utils/       logging, metrics, time parsing, file janitor
"""

__version__ = "0.1.0"
