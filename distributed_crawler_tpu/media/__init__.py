"""media/: distributed ASR serving — crawled audio to transcripts.

The multi-modal leg of the serving pipeline (BASELINE config #4): the
crawl-side `MediaBridge` publishes fetched audio refs as typed
`AudioBatchMessage`s, the `AudioChunker` turns ragged waveforms into
bucketed fixed-shape window batches (one compiled Whisper program per
bucket, per the PR-1 packing discipline), the `ASRWorker` serves them
with the same queue/ack/telemetry/SLO machinery as the text TPU worker,
and `TranscriptReentry` feeds transcripts back through the
`InferenceBridge` so they get embedded and classified like any crawled
post.
"""

from .bridge import MediaBridge, TranscriptReentry
from .chunker import (
    DEFAULT_WINDOW_BUCKETS,
    AudioChunker,
    ChunkPlan,
    bucket_for_windows,
)
from .worker import ASRWorker, ASRWorkerConfig, iter_transcripts

__all__ = [
    "ASRWorker",
    "ASRWorkerConfig",
    "AudioChunker",
    "ChunkPlan",
    "DEFAULT_WINDOW_BUCKETS",
    "MediaBridge",
    "TranscriptReentry",
    "bucket_for_windows",
    "iter_transcripts",
]
