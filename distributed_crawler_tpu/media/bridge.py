"""The crawl -> ASR bridge and the transcript re-entry hop.

`MediaBridge` is the media twin of `inference/bridge.py:InferenceBridge`:
it decorates any StateManager, watches the media write path
(`telegram/parsing.py:fetch_and_upload_media` calls
``notify_media_stored`` on the manager after a successful store — plain
managers simply don't implement it), accumulates audio refs, and
publishes typed `AudioBatchMessage`s on ``TOPIC_MEDIA_BATCHES`` with a
deadline flush, so a bursty crawl can't strand refs below the batch
size.  Dedup is two-layered: the `ShardedMediaCache` upstream keeps
already-processed media from being re-fetched at all, and a bounded
recently-seen window here keeps at-least-once re-crawls from
re-publishing a ref that already shipped (same discipline as the
InferenceBridge's post_uid window).

`TranscriptReentry` closes the loop: it subscribes to
``TOPIC_TRANSCRIPTS`` and feeds each successful transcript back through
an `InferenceBridge`-wrapped manager as a synthetic text post whose
``post_uid`` is the deterministic ``media:<media_id>`` — so the PR-7
dedupe window holds across re-crawls and redeliveries, and the existing
text path embeds/classifies transcripts like any crawled post.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from typing import List, Optional

from ..bus.messages import (
    TOPIC_MEDIA_BATCHES,
    TOPIC_TRANSCRIPTS,
    AudioBatchMessage,
    AudioRef,
    TranscriptMessage,
)
from ..datamodel import Post
from ..utils import trace

logger = logging.getLogger("dct.media.bridge")

# Containers the ASR stage can decode today (PCM wav; everything else is
# an upstream ffmpeg concern — see `inference/asr.py`).
AUDIO_EXTENSIONS = (".wav",)


class MediaBridge:
    """StateManager decorator publishing audio-ref batches as media lands."""

    def __init__(self, sm, bus, crawl_id: str = "", batch_size: int = 8,
                 deadline_s: float = 0.25, topic: str = TOPIC_MEDIA_BATCHES,
                 poll_interval_s: float = 0.05, dedupe_window: int = 65536,
                 extensions: tuple = AUDIO_EXTENSIONS, tenant: str = ""):
        self._sm = sm
        self._bus = bus
        self._topic = topic
        self._crawl_id = crawl_id
        # Tenant provenance (ISSUE 17): stamped onto every published
        # audio batch; empty folds to the documented default tenant.
        self._tenant = tenant
        self._batch_size = max(1, batch_size)
        self._deadline_s = deadline_s
        self._extensions = tuple(e.lower() for e in extensions)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pending: List[AudioRef] = []
        self._first_at: Optional[float] = None
        self.batches_published = 0
        self.refs_bridged = 0
        self.refs_deduped = 0
        self.refs_skipped = 0          # non-audio media
        self.publish_failures = 0
        self._retry_at = 0.0           # backoff gate after a failed publish
        self._fail_streak = 0
        self._dedupe_window = max(0, dedupe_window)
        self._seen_ids: "OrderedDict[str, None]" = OrderedDict()
        self._thread = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="dct-media-bridge-flush")
        self._poll_interval_s = poll_interval_s
        self._thread.start()

    # -- the media write hook ----------------------------------------------
    def notify_media_stored(self, media_id: str, path: str,
                            channel_name: str = "",
                            post_uid: str = "") -> None:
        """Called by `fetch_and_upload_media` after a successful store
        (and by tests/loadgen directly).  Non-audio containers are
        counted and skipped; duplicate media ids inside the window are
        dropped — the `ShardedMediaCache` already stopped re-fetches,
        this stops re-publishes on at-least-once re-crawls."""
        if not media_id or not path:
            return
        if not path.lower().endswith(self._extensions):
            with self._lock:
                self.refs_skipped += 1
            return
        ref = AudioRef(media_id=media_id, path=path,
                       channel_name=channel_name, post_uid=post_uid)
        now = time.monotonic()
        with self._lock:
            if self._dedupe_window:
                if media_id in self._seen_ids:
                    self._seen_ids.move_to_end(media_id)
                    self.refs_deduped += 1
                    return
                self._seen_ids[media_id] = None
                while len(self._seen_ids) > self._dedupe_window:
                    self._seen_ids.popitem(last=False)
            self.refs_bridged += 1
            if self._first_at is None:
                self._first_at = now
            self._pending.append(ref)
            # The retry-backoff gate applies here too, or a full batch
            # arriving mid-outage would hammer the dead bus per ref.
            batch = self._emit() \
                if (len(self._pending) >= self._batch_size
                    and now >= self._retry_at) else None
        if batch is not None:
            self._publish(batch)

    # -- lifecycle ----------------------------------------------------------
    def flush(self) -> None:
        """Ship whatever is accumulated (end of crawl / shutdown)."""
        with self._lock:
            batch = self._emit() if self._pending else None
        if batch is not None:
            self._publish(batch)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self.flush()
        self._sm.close()

    def _emit(self) -> AudioBatchMessage:
        """Build a batch from pending refs; every caller holds the lock
        (the crawlint pragma records that contract)."""
        msg = AudioBatchMessage.new(self._pending, crawl_id=self._crawl_id,
                                    tenant=self._tenant)
        self._pending = []  # crawlint: disable=LCK001
        self._first_at = None  # crawlint: disable=LCK001
        return msg

    def _poll_loop(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            now = time.monotonic()
            with self._lock:
                due = (self._pending and self._first_at is not None
                       and now >= self._retry_at
                       and now - self._first_at >= self._deadline_s)
                batch = self._emit() if due else None
            if batch is not None:
                self._publish(batch)

    def _publish(self, msg: AudioBatchMessage) -> None:
        """Publish one batch; a failure REQUEUES the refs into the
        accumulator (with backoff) instead of dropping them.

        Dropping here would be permanent loss: the ids are already in
        the dedupe window and `fetch_and_upload_media` marked them
        processed in the ShardedMediaCache before notifying, so neither
        a re-notify nor a re-crawl would ever retry them.  The deadline
        flusher retries the requeued refs once ``_retry_at`` passes
        (exponential backoff, capped at 5 s)."""
        try:
            # Root span of the media batch's trace — the ASR worker's
            # queue-wait/decode/transcribe spans and the transcript's
            # re-entry hop all share msg.trace_id.
            with trace.span("media.dispatch", trace_id=msg.trace_id,
                            batch=msg.batch_id, refs=len(msg.refs),
                            crawl_id=msg.crawl_id):
                self._bus.publish(self._topic, msg.to_dict())
            with self._lock:
                self.batches_published += 1
                self._fail_streak = 0
                self._retry_at = 0.0
        except Exception as e:
            with self._lock:
                self.publish_failures += 1
                self._fail_streak += 1
                self._retry_at = time.monotonic() + min(
                    5.0, 0.25 * (2 ** min(self._fail_streak, 5)))
                # Requeue at the front so retry order stays stable; the
                # batch id/trace id are reminted on the retry emit.
                self._pending = list(msg.refs) + self._pending
                if self._first_at is None:
                    self._first_at = time.monotonic() - self._deadline_s
            logger.error("failed to publish audio batch (requeued)",
                         extra={"batch_id": msg.batch_id,
                                "refs": len(msg.refs), "error": str(e)})

    # -- everything else is the wrapped manager -----------------------------
    def __getattr__(self, name):
        return getattr(self._sm, name)


class TranscriptReentry:
    """TOPIC_TRANSCRIPTS -> synthetic text posts through a bridged manager.

    ``sm`` should be (or wrap) an `InferenceBridge`, so each stored post
    ships to the inference topic and the text path embeds/classifies it;
    a plain manager still stores the transcript post in the crawl sink.
    Error transcripts (decode failures) are counted, not stored — an
    empty post would just burn an embed slot.
    """

    def __init__(self, sm, bus=None, topic: str = TOPIC_TRANSCRIPTS):
        self._sm = sm
        self.posts_reentered = 0
        self.errors_skipped = 0
        if bus is not None:
            bus.subscribe(topic, self.handle_transcript)

    def handle_transcript(self, payload: dict) -> None:
        try:
            msg = TranscriptMessage.from_dict(payload)
            msg.validate()
        except Exception as e:
            logger.warning("undecodable transcript payload dropped: %s", e)
            return
        if msg.error or not (msg.text or msg.tokens):
            self.errors_skipped += 1
            return
        text = msg.text or " ".join(str(t) for t in msg.tokens)
        channel = msg.channel_name or \
            (os.path.dirname(msg.path) or "transcripts")
        post = Post(
            post_uid=msg.post_uid or f"media:{msg.media_id}",
            channel_id=channel,
            channel_name=channel,
            platform_name="telegram",
            post_type=["audio_transcript"],
            description=text,
        )
        # The re-entry hop joins the transcript's trace (itself the audio
        # batch's), linking the media leg to the text leg's record batch.
        with trace.span("media.reentry", trace_id=msg.trace_id,
                        media_id=msg.media_id, post_uid=post.post_uid):
            self._sm.store_post(post.channel_id, post)
        self.posts_reentered += 1
