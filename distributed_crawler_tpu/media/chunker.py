"""Ragged-audio scheduler: waveforms -> fixed 30 s windows -> bucketed
batches -> per-file reassembly.

Whisper's compiled program is shape-static twice over: every utterance
is a fixed ``window_samples`` waveform (30 s for the real configs), and
the batch dimension must be one of a few compiled sizes.  Crawled media
is ragged on both axes — files run from 2-second voice notes to
hour-long videos — so this module is the host-side quantizer, the audio
twin of `ops/padding` for text:

- :meth:`AudioChunker.chunk` slices each decoded waveform into fixed
  windows (zero-padded tail) and keeps a **segment map** from every
  window back to its (file, window-index) origin — reassembly is a
  deterministic walk of that map, never a guess;
- :meth:`AudioChunker.batches` greedily fills the LARGEST window-count
  bucket first, then the smallest bucket that covers the remainder —
  one compiled program per bucket, zero per-fill recompiles (the PR-1
  bucketing discipline applied to the batch axis);
- padding accounting (real windows vs slot windows, real samples vs
  slot samples) feeds the PR-5 efficiency meters so an ASR stream
  drifting into pathological fill levels is visible on /costs.

Decode failures are *explicit*: a file that cannot be read contributes
zero windows and an entry in ``ChunkPlan.errors`` — downstream emits an
error transcript for it instead of silently dropping or reordering
(the `transcribe_files` result-ordering bug this PR fixes).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("dct.media.chunker")

# Window-count buckets for the batch axis: 8 is `inference.asr_batch_size`'s
# default, and powers of two below it cover stragglers with at most 2x
# slot waste on the final partial batch.
DEFAULT_WINDOW_BUCKETS = (1, 2, 4, 8)


def bucket_for_windows(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n; the largest bucket when none covers (callers
    split to the largest bucket first, so this only sees n <= max)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclass
class ChunkPlan:
    """The chunker's output: windows + the map back to source files.

    ``segment_map[w] == (file_index, window_index)`` — window ``w`` of
    the plan is window ``window_index`` of input file ``file_index``.
    Windows of one file are always contiguous and in order, so
    :meth:`AudioChunker.reassemble` is a single ordered walk.
    """

    window_samples: int
    windows: np.ndarray                  # [n_windows, window_samples] f32
    segment_map: List[Tuple[int, int]] = field(default_factory=list)
    n_files: int = 0
    errors: Dict[int, str] = field(default_factory=dict)
    real_samples: List[int] = field(default_factory=list)  # per window

    @property
    def n_windows(self) -> int:
        return len(self.segment_map)

    def windows_per_file(self) -> List[int]:
        counts = [0] * self.n_files
        for file_idx, _ in self.segment_map:
            counts[file_idx] += 1
        return counts


@dataclass
class WindowBatch:
    """One device dispatch: ``audio`` is padded to ``bucket`` rows (the
    compiled batch size); ``window_indices`` name the plan windows that
    occupy the real rows, in row order."""

    bucket: int
    audio: np.ndarray                    # [bucket, window_samples] f32
    window_indices: List[int]

    @property
    def real_windows(self) -> int:
        return len(self.window_indices)

    @property
    def pad_windows(self) -> int:
        return self.bucket - len(self.window_indices)


class AudioChunker:
    """Decode + window + bucket ragged audio into static shapes."""

    def __init__(self, window_samples: int,
                 buckets: Sequence[int] = DEFAULT_WINDOW_BUCKETS,
                 max_windows_per_file: int = 0,
                 reader: Optional[Callable[[str], np.ndarray]] = None):
        if window_samples <= 0:
            raise ValueError(f"window_samples must be positive, "
                             f"got {window_samples}")
        cleaned = sorted({int(b) for b in buckets if int(b) > 0})
        if not cleaned:
            raise ValueError(f"no positive window buckets in {buckets!r}")
        self.window_samples = int(window_samples)
        self.buckets = tuple(cleaned)
        # 0 = unbounded; >0 caps pathological inputs (an hour-long video
        # is 120 windows — a cap turns it into "first N windows" rather
        # than a batch that starves every neighbor).
        self.max_windows_per_file = max(0, int(max_windows_per_file))
        if reader is None:
            from ..inference.asr import read_wav_mono_16k

            reader = read_wav_mono_16k
        self._reader = reader

    # -- decode --------------------------------------------------------------
    def read(self, path: str) -> np.ndarray:
        """Decode one file to a float32 mono 16 kHz waveform (raises on
        failure; `chunk_files` catches per file)."""
        return np.asarray(self._reader(path), np.float32)

    # -- windowing -----------------------------------------------------------
    def split(self, audio: np.ndarray) -> List[np.ndarray]:
        """One waveform -> fixed windows (zero-padded tail).  An empty
        waveform still yields one silent window: the file was readable,
        so it must produce a transcript row, not vanish."""
        w = self.window_samples
        audio = np.asarray(audio, np.float32).reshape(-1)
        n = max(1, -(-len(audio) // w))  # ceil; >=1 window always
        if self.max_windows_per_file:
            n = min(n, self.max_windows_per_file)
        out = []
        for i in range(n):
            chunk = audio[i * w:(i + 1) * w]
            if len(chunk) < w:
                chunk = np.pad(chunk, (0, w - len(chunk)))
            out.append(chunk)
        return out

    def chunk(self, audios: Sequence[Optional[np.ndarray]],
              errors: Optional[Dict[int, str]] = None) -> ChunkPlan:
        """Waveforms (None = decode failure) -> a deterministic ChunkPlan.

        Determinism matters: the same inputs must produce the same window
        order, segment map, and bucket batches on every worker generation,
        so a killed-and-requeued batch writes back byte-identical rows.
        """
        plan = ChunkPlan(window_samples=self.window_samples,
                         windows=np.zeros((0, self.window_samples),
                                          np.float32),
                         n_files=len(audios), errors=dict(errors or {}))
        rows: List[np.ndarray] = []
        for file_idx, audio in enumerate(audios):
            if audio is None:
                plan.errors.setdefault(file_idx, "decode failed")
                continue
            real_len = int(np.asarray(audio).reshape(-1).shape[0])
            for win_idx, row in enumerate(self.split(audio)):
                rows.append(row)
                plan.segment_map.append((file_idx, win_idx))
                consumed = win_idx * self.window_samples
                plan.real_samples.append(
                    max(1, min(self.window_samples, real_len - consumed)))
        if rows:
            plan.windows = np.stack(rows)
        return plan

    def chunk_files(self, paths: Sequence[str]) -> ChunkPlan:
        """Decode + chunk a path list; per-file failures land in
        ``plan.errors`` (input order preserved by construction)."""
        audios: List[Optional[np.ndarray]] = []
        errors: Dict[int, str] = {}
        for i, path in enumerate(paths):
            try:
                audios.append(self.read(path))
            except Exception as e:
                logger.error("failed to read %s: %s", path, e)
                errors[i] = f"{type(e).__name__}: {e}"
                audios.append(None)
        return self.chunk(audios, errors=errors)

    # -- bucketed batches ----------------------------------------------------
    def batches(self, plan: ChunkPlan) -> List[WindowBatch]:
        """Split the plan's windows into bucket-sized device batches.

        Greedy largest-bucket-first: full batches at the top bucket, then
        the smallest bucket covering the remainder — every dispatch hits
        a program that already exists after warmup.
        """
        out: List[WindowBatch] = []
        top = self.buckets[-1]
        idx = list(range(plan.n_windows))
        pos = 0
        while pos < len(idx):
            remaining = len(idx) - pos
            bucket = top if remaining >= top \
                else bucket_for_windows(remaining, self.buckets)
            take = idx[pos:pos + min(bucket, remaining)]
            pos += len(take)
            audio = np.zeros((bucket, self.window_samples), np.float32)
            audio[:len(take)] = plan.windows[take]
            out.append(WindowBatch(bucket=bucket, audio=audio,
                                   window_indices=take))
        return out

    def padding_stats(self, plan: ChunkPlan,
                      batches: Sequence[WindowBatch]) -> Dict[str, float]:
        """Real-vs-slot accounting for the PR-5 efficiency meters."""
        slot_windows = sum(b.bucket for b in batches)
        real_windows = sum(b.real_windows for b in batches)
        slot_samples = slot_windows * self.window_samples
        real_samples = sum(plan.real_samples)
        return {
            "real_windows": real_windows,
            "slot_windows": slot_windows,
            "real_samples": real_samples,
            "slot_samples": slot_samples,
            "window_density": real_windows / slot_windows
            if slot_windows else 0.0,
            "sample_density": real_samples / slot_samples
            if slot_samples else 0.0,
        }

    # -- reassembly ----------------------------------------------------------
    @staticmethod
    def reassemble(plan: ChunkPlan,
                   per_window: Sequence[Sequence[int]]
                   ) -> List[List[int]]:
        """Fan per-window token lists back to per-file lists, input order.

        ``per_window[w]`` is the (special-stripped) token output of plan
        window ``w``.  Files with decode errors get an empty list — the
        caller pairs them with ``plan.errors`` for explicit failure rows.
        """
        if len(per_window) != plan.n_windows:
            raise ValueError(
                f"{len(per_window)} window outputs for {plan.n_windows} "
                f"plan windows")
        out: List[List[int]] = [[] for _ in range(plan.n_files)]
        for w, (file_idx, _win_idx) in enumerate(plan.segment_map):
            out[file_idx].extend(int(t) for t in per_window[w])
        return out
