"""ASR worker service: audio-ref batches in, transcripts out.

The media twin of `inference/worker.py:TPUWorker`, shaped the same way on
purpose — one serving discipline across modalities:

- the bus handler only decodes and enqueues (never blocks on the device);
  queue wait is a span of each batch's own trace;
- the feed loop drains up to ``coalesce_batches`` queued audio batches
  per dispatch group so their windows share bucketed device batches
  (`media/chunker.py`) instead of each partial batch padding up alone;
- per-batch ack/poison isolation: each `AudioBatchMessage` keeps its OWN
  transcript publish + idempotent writeback + ack, a file that fails to
  decode becomes an explicit error transcript, and a failed combined
  device step falls back to per-batch execution so one poisoned batch
  cannot take its coalesced neighbors down;
- telemetry-rich heartbeats (``worker_type="asr"``) feed the
  orchestrator's FleetView; the SLO watchdog evaluates the new
  ``slo_asr_batch_p95_ms`` budget (plus the shared queue-wait and
  batch-age budgets) each beat;
- ``kill()`` / ``evaluate_slos()`` are the loadgen chaos seams, with the
  same abrupt-death semantics as the TPU worker's.

Results land as one JSONL file per batch under
``{storage_prefix}/{crawl_id}/batches/{batch_id}.jsonl`` (idempotent:
redeliveries overwrite the same file with the same content), and every
transcript is also announced on ``TOPIC_TRANSCRIPTS`` for the re-entry
hop (`media/bridge.py:TranscriptReentry`).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..bus.messages import (
    MSG_HEARTBEAT,
    MSG_WORKER_STOPPING,
    TOPIC_MEDIA_BATCHES,
    TOPIC_SPANS,
    TOPIC_TRANSCRIPTS,
    TOPIC_WORKER_STATUS,
    AudioBatchMessage,
    SpanBatchMessage,
    StatusMessage,
    TranscriptMessage,
    WORKER_BUSY,
    WORKER_IDLE,
    WORKER_OFFLINE,
)
from ..utils import flight, trace
from ..utils.occupancy import QueueDepthSampler
from ..utils.metrics import (
    REGISTRY,
    MetricsRegistry,
    clear_costs_provider,
    clear_status_provider,
    serve_metrics,
    set_costs_provider,
    set_status_provider,
)
from ..utils.slo import SLOWatchdog, standard_slos
from ..utils.telemetry import TelemetryEmitter
from ..utils.timeseries import RegistrySampler

logger = logging.getLogger(__name__)


def iter_transcripts(provider, crawl_id: str,
                     storage_prefix: str = "asr"):
    """Yield transcript rows across all per-batch files of a crawl, in
    batch-file order — the read side of the idempotent writeback (the
    loadgen gate's media-id reconciliation source)."""
    base = f"{storage_prefix}/{crawl_id}/batches"
    for name in provider.list_dir(base):
        if not name.endswith(".jsonl"):
            continue
        text = provider.get_text(f"{base}/{name}")
        for line in (text or "").splitlines():
            if line:
                yield json.loads(line)


@dataclass
class ASRWorkerConfig:
    worker_id: str = "asr-worker-0"
    heartbeat_s: float = 30.0
    queue_capacity: int = 64          # decoded audio batches awaiting device
    metrics_port: int = 0             # 0 = don't serve; >0 = HTTP port
    storage_prefix: str = "asr"
    # Transcript rows carry token ids; set False to drop them from the
    # writeback (text only) when the vocab is wired and rows get fat.
    write_tokens: bool = True
    # Coalescing feed: one dequeue drains up to this many queued audio
    # batches and runs their windows through shared bucketed device
    # batches; every AudioBatchMessage still gets its own ack/writeback.
    coalesce_batches: int = 2
    # SLO budgets (`utils/slo.py`), evaluated once per heartbeat; 0 = no
    # budget declared.  asr_batch is the new per-group budget; queue_wait
    # and batch_age share the text worker's budget families (the ASR
    # spans are members of the same span sets).
    slo_asr_batch_p95_ms: float = 0.0
    slo_queue_wait_ms: float = 0.0
    slo_batch_age_ms: float = 0.0
    # Span export (the TPU worker's mirror): completed spans ship as
    # SpanBatchMessages on TOPIC_SPANS for /dtraces assembly.  0 = off.
    span_export_interval_s: float = 15.0
    span_export_max_spans: int = 512
    span_sample_rate: float = 1.0


class ASRWorker:
    """Consume AudioBatchMessages, run the ASR pipeline, publish
    transcripts + write results.

    ``pipeline`` is an `inference.asr.ASRPipeline` (or anything with its
    ``chunker`` / ``transcribe_plan`` / ``cost_snapshot`` surface);
    ``provider`` any `state.providers.StorageProvider`.
    """

    def __init__(self, bus, pipeline,
                 provider=None,
                 cfg: ASRWorkerConfig = ASRWorkerConfig(),
                 registry: MetricsRegistry = REGISTRY):
        self.bus = bus
        self.pipeline = pipeline
        self.provider = provider
        self.cfg = cfg
        self._queue: "queue.Queue[Tuple[AudioBatchMessage, Any, float]]" = \
            queue.Queue(cfg.queue_capacity)
        self._stop = threading.Event()
        self._threads: list = []
        self._idle = threading.Condition()
        self._inflight = 0
        self._started_at = 0.0
        self._processed = 0
        self._errors = 0
        self._metrics_server = None
        self._killed = False
        self._stop_announced = False
        self.m_queue_depth = registry.gauge(
            "asr_worker_queue_depth",
            "decoded audio batches awaiting device (time-weighted "
            "rolling mean — an edge-triggered gauge aliases between "
            "scrapes)")
        self._depth = QueueDepthSampler(self.m_queue_depth)
        self.m_batches = registry.counter(
            "asr_worker_batches_total", "audio batches processed")
        self.m_media = registry.counter(
            "asr_worker_media_total", "media files transcribed (incl. "
            "explicit error rows)")
        self.m_batch_age = registry.histogram(
            "asr_worker_batch_age_seconds",
            "bus transit + queue wait per audio batch")
        self.m_coalesce = registry.histogram(
            "asr_worker_coalesced_group_batches",
            "audio batches coalesced into one device group")
        self.m_outcomes = registry.counter(
            "asr_worker_batch_outcomes_total",
            "audio batches by final commit outcome")
        self._telemetry = TelemetryEmitter(
            engine=pipeline, include_device=True,
            counters={"batch_outcomes": self.m_outcomes})
        self._slo = SLOWatchdog(
            standard_slos(queue_wait_ms=cfg.slo_queue_wait_ms,
                          batch_age_ms=cfg.slo_batch_age_ms,
                          asr_batch_p95_ms=cfg.slo_asr_batch_p95_ms),
            registry=registry)
        # Watchtower self-sampling (utils/timeseries.py): this worker's
        # registry becomes rolling series once per heartbeat, so its
        # /timeseries history survives orchestrator restarts.
        self._ts_sampler = RegistrySampler(registry)
        # Ownership-filtered like the TPU worker's: in the ASR + reentry
        # shared-process topology the text worker ships engine.* spans,
        # this worker ships the ASR stages PLUS media.reentry — the
        # TranscriptReentry hop runs in the asr-worker process
        # (cli._build_asr_worker), so without it the reentry leg would
        # never reach /dtraces in a real multi-process deployment.
        self._span_exporter = trace.SpanExporter(
            max_spans=cfg.span_export_max_spans,
            sample_rate=cfg.span_sample_rate,
            name_prefixes=("asr_worker.", "asr.", "media.reentry"))
        self._last_span_export = time.monotonic()

    # -- status/costs --------------------------------------------------------
    def get_status(self) -> dict:
        return {
            "worker_id": self.cfg.worker_id,
            "model": "whisper",
            "is_running": not self._stop.is_set() and bool(self._threads),
            "queue_depth": self._queue.qsize(),
            "inflight": self._inflight,
            "processed_batches": self._processed,
            "error_batches": self._errors,
            "uptime_s": (time.monotonic() - self._started_at)
            if self._started_at else 0.0,
        }

    def get_costs(self) -> dict:
        """The /costs body: Whisper program rows + efficiency window +
        this worker's SLO state + per-tenant spend rows."""
        snap_fn = getattr(self.pipeline, "cost_snapshot", None)
        out = dict(snap_fn()) if callable(snap_fn) else {}
        out["worker_id"] = self.cfg.worker_id
        out["slo"] = self._slo.snapshot()
        ledger = self._tenant_ledger()
        if ledger is not None:
            out["tenants"] = ledger.snapshot()
        return out

    # -- tenant attribution (ISSUE 17) ---------------------------------------
    def _tenant_ledger(self):
        return getattr(getattr(self.pipeline, "meter", None),
                       "tenants", None)

    def _set_meter_tenants(self, weights: Dict[str, float]) -> None:
        set_fn = getattr(getattr(self.pipeline, "meter", None),
                         "set_tenants", None)
        if callable(set_fn):
            set_fn(weights)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._started_at = time.monotonic()
        set_status_provider(self.get_status)
        set_costs_provider(self.get_costs)
        self.bus.subscribe(TOPIC_MEDIA_BATCHES, self._handle_payload)
        for target, name in ((self._feed_loop, "asr-feed"),
                             (self._heartbeat_loop, "asr-heartbeat")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        if self.cfg.metrics_port:
            self._metrics_server = serve_metrics(self.cfg.metrics_port)
        logger.info("asr worker started", extra={
            "worker_id": self.cfg.worker_id})

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        clear_status_provider(self.get_status)
        clear_costs_provider(self.get_costs)
        for t in self._threads:
            t.join(timeout=timeout_s)
        if self.cfg.span_export_interval_s > 0:
            # Graceful stop ships the span tail (kill() deliberately
            # doesn't — a crashed process exports nothing).
            self.export_spans()
        # Clean-shutdown announcement (the TPU worker's mirror): the
        # fleet view marks this worker OFFLINE instead of aging it into
        # "stale" — what autoscaler retirement relies on.
        self._announce_stopping()
        if self.provider is not None:
            flush = getattr(self.provider, "flush", None)
            if callable(flush):
                flush()
        if self._metrics_server is not None:
            self._metrics_server.shutdown()

    def _announce_stopping(self) -> None:
        """Best-effort worker_stopping status on graceful stop;
        idempotent, and silent after kill() (SIGKILL fidelity)."""
        if self._killed or self._stop_announced:
            return
        self._stop_announced = True
        try:
            self.bus.publish(TOPIC_WORKER_STATUS, StatusMessage.new(
                self.cfg.worker_id, MSG_WORKER_STOPPING, WORKER_OFFLINE,
                tasks_processed=self._processed,
                tasks_success=self._processed - self._errors,
                tasks_error=self._errors,
                uptime_s=time.monotonic() - self._started_at,
                worker_type="asr").to_dict())
        except Exception as e:  # a dead bus must not break shutdown
            logger.debug("stopping announcement failed: %s", e)

    def kill(self) -> None:
        """Abrupt-death chaos seam (the TPU worker's `kill()` twin): halt
        the feed/heartbeat threads WITHOUT draining or acking — un-acked
        frames requeue server-side once the caller tears this worker's
        pull stream down; providers stay registered, exactly as a dead
        process leaves its endpoints unreachable, not deregistered."""
        self._killed = True
        self._stop.set()
        flight.record("worker_kill", worker=self.cfg.worker_id,
                      queue_depth=self._queue.qsize(),
                      inflight=self._inflight)
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()

    def evaluate_slos(self) -> list:
        """One on-demand SLO tick (the loadgen gate calls this at phase
        boundaries so breach attribution is deterministic)."""
        return self._slo.evaluate()

    def export_spans(self) -> int:
        """Ship spans completed since the last export on TOPIC_SPANS
        (the TPU worker's mirror); returns the count shipped.  Never
        raises into the serving path."""
        try:
            spans, dropped = self._span_exporter.collect()
            if not spans and not dropped:
                return 0
            msg = SpanBatchMessage.new(
                self.cfg.worker_id, [s.to_dict() for s in spans],
                dropped=dropped)
            self.bus.publish(TOPIC_SPANS, msg.to_dict())
            return len(spans)
        except Exception as e:
            logger.warning("span export failed: %s", e)
            return 0

    def drain(self, timeout_s: float = 30.0) -> bool:
        with self._idle:
            return self._idle.wait_for(
                lambda: self._inflight == 0, timeout=timeout_s)

    def warmup(self) -> None:
        """Pre-compile every window-bucket program before serving."""
        warm = getattr(self.pipeline, "warmup", None)
        if callable(warm):
            warm()

    # -- bus handler (never blocks on the device) ----------------------------
    def _handle_payload(self, payload: Dict[str, Any], ack=None) -> None:
        """``ack`` is supplied by manual-ack buses (RemoteBus); the frame
        is acked only after transcripts are published AND written back."""
        try:
            msg = AudioBatchMessage.from_dict(payload)
        except Exception as e:
            # Undecodable envelope: poison at the wire layer.  Nack so a
            # manual-ack bus dead-letters/requeues per its policy; there
            # is nothing to write back.
            logger.error("undecodable audio batch payload: %s", e)
            if ack is not None:
                ack(False)
            return
        if not msg.refs:
            if ack is not None:
                ack(True)
            return
        with self._idle:
            self._inflight += 1
        try:
            self._queue.put((msg, ack, time.monotonic()), timeout=5.0)
        except queue.Full:
            self._finish_one()
            if ack is not None:
                self.m_outcomes.labels(outcome="requeued").inc()
                flight.record("asr_batch", batch=msg.batch_id,
                              outcome="requeued", reason="queue_full")
                ack(False)
                return
            raise
        self._depth.update(self._queue.qsize())

    def _finish_one(self) -> None:
        with self._idle:
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.notify_all()

    # -- feed loop (coalescing) ----------------------------------------------
    def _feed_loop(self) -> None:
        timeline = getattr(self.pipeline, "timeline", None)
        while not self._stop.is_set():
            try:
                items = [self._queue.get(timeout=0.1)]
            except queue.Empty:
                # Queue dry = idle-by-no-work: the next dispatch opens a
                # new occupancy stream, never a pipeline bubble.
                if timeline is not None:
                    timeline.start_stream()
                continue
            while len(items) < max(1, self.cfg.coalesce_batches):
                try:
                    items.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            self._depth.update(self._queue.qsize())
            try:
                self._process_group(items)
            finally:
                for _ in items:
                    self._finish_one()

    def _process_group(
            self,
            items: List[Tuple[AudioBatchMessage, Any, float]]) -> None:
        now = time.monotonic()
        ledger = self._tenant_ledger()
        for msg, _, enq_t in items:
            trace.record("asr_worker.queue_wait", now - enq_t,
                         trace_id=msg.trace_id, batch=msg.batch_id,
                         worker=self.cfg.worker_id, tenant=msg.tenant)
            if ledger is not None and msg.tenant:
                ledger.observe_queue_wait(msg.tenant, now - enq_t)
            self._observe_age(msg)
        if len(items) == 1:
            msg, ack, _ = items[0]
            self._process_one(msg, ack)
            return
        self.m_coalesce.observe(len(items))
        # Decode + chunk per batch FIRST: a ref that fails to decode
        # becomes that batch's error row, never a neighbor's problem.
        plans = []
        for msg, ack, _ in items:
            plans.append(self._chunk(msg))
        # Tenant weights for the combined dispatch = window counts.
        weights: Dict[str, float] = {}
        for (msg, _, _), plan in zip(items, plans):
            if plan is not None:
                weights[msg.tenant] = weights.get(msg.tenant, 0.0) \
                    + max(1, plan.n_windows)
        self._set_meter_tenants(weights)
        dominant = max(weights, key=weights.get) if weights else ""
        # One combined window list across the group -> shared bucketed
        # device batches; per-batch window counts fan results back.
        try:
            with trace.span("asr_worker.coalesce",
                            trace_id=items[0][0].trace_id,
                            batches=len(items),
                            batch_ids=[m.batch_id for m, _, _ in items],
                            windows=sum(p.n_windows for p in plans
                                        if p is not None),
                            tenant=dominant):
                merged = self._merge_plans([p for p in plans
                                            if p is not None])
                per_window = self.pipeline.transcribe_plan(merged) \
                    if merged is not None else []
        except Exception as e:
            logger.exception(
                "coalesced ASR step over %d batches failed (%s); "
                "isolating per batch", len(items), e)
            for (msg, ack, _), plan in zip(items, plans):
                self._process_isolated(msg, ack, plan)
            return
        off = 0
        for (msg, ack, _), plan in zip(items, plans):
            if plan is None:
                self._fail_batch(msg, ack, "chunking failed")
                continue
            rows = per_window[off:off + plan.n_windows]
            off += plan.n_windows
            self._finish_batch(msg, ack, plan, lambda rows=rows: rows)

    def _merge_plans(self, plans):
        """Concatenate ChunkPlans into one (file indices offset) so the
        group's windows share bucket batches."""
        import numpy as np

        from .chunker import ChunkPlan

        plans = [p for p in plans if p is not None]
        if not plans:
            return None
        merged = ChunkPlan(
            window_samples=plans[0].window_samples,
            windows=np.concatenate([p.windows for p in plans])
            if any(p.n_windows for p in plans)
            else plans[0].windows[:0])
        base = 0
        for p in plans:
            merged.segment_map.extend(
                (base + fi, wi) for fi, wi in p.segment_map)
            merged.errors.update({base + i: e for i, e in p.errors.items()})
            merged.real_samples.extend(p.real_samples)
            base += p.n_files
        merged.n_files = base
        return merged

    def _chunk(self, msg: AudioBatchMessage):
        """Decode + window one batch's refs; None only on a total chunker
        failure (per-file failures are plan.errors entries)."""
        try:
            with trace.span("asr_worker.chunk", trace_id=msg.trace_id,
                            batch=msg.batch_id, refs=len(msg.refs)):
                return self.pipeline.chunker.chunk_files(
                    [r.path for r in msg.refs])
        except Exception as e:
            logger.exception("batch %s failed to chunk: %s",
                             msg.batch_id, e)
            return None

    # -- single-batch paths --------------------------------------------------
    def _process_one(self, msg: AudioBatchMessage, ack) -> None:
        plan = self._chunk(msg)
        self._process_isolated(msg, ack, plan)

    def _process_isolated(self, msg: AudioBatchMessage, ack, plan) -> None:
        if plan is None:
            self._fail_batch(msg, ack, "chunking failed")
            return

        def produce():
            self._set_meter_tenants({msg.tenant: max(1, plan.n_windows)})
            with trace.span("asr_worker.process", trace_id=msg.trace_id,
                            batch=msg.batch_id, refs=len(msg.refs),
                            windows=plan.n_windows, tenant=msg.tenant):
                return self.pipeline.transcribe_plan(plan)

        self._finish_batch(msg, ack, plan, produce)

    # -- commit / ack (the ONE copy every path shares) -----------------------
    def _finish_batch(self, msg: AudioBatchMessage, ack, plan,
                      produce) -> None:
        try:
            per_window = produce()
            transcripts = self._assemble(msg, plan, per_window)
            with trace.span("asr_worker.commit", trace_id=msg.trace_id,
                            batch=msg.batch_id, refs=len(msg.refs)):
                self._commit(msg, transcripts)
            self._processed += 1
            self.m_outcomes.labels(outcome="ok").inc()
            flight.record("asr_batch", batch=msg.batch_id, outcome="ok",
                          refs=len(msg.refs), windows=plan.n_windows)
            self._ack(msg, ack, True)
        except Exception as e:
            self._fail_batch(msg, ack, str(e), exc=True)

    def _fail_batch(self, msg: AudioBatchMessage, ack, reason: str,
                    exc: bool = False) -> None:
        self._errors += 1
        self.m_outcomes.labels(outcome="error").inc()
        flight.record("asr_batch", batch=msg.batch_id, outcome="error",
                      error=reason)
        if exc:
            logger.exception("audio batch %s failed: %s",
                             msg.batch_id, reason)
        else:
            logger.error("audio batch %s failed: %s", msg.batch_id, reason)
        self._ack(msg, ack, False)

    def _ack(self, msg: AudioBatchMessage, ack, ok: bool) -> None:
        if ack is None:
            return
        t0 = time.perf_counter()
        ack(ok)
        trace.record("asr_worker.ack", time.perf_counter() - t0,
                     trace_id=msg.trace_id, batch=msg.batch_id, ok=ok)

    def _assemble(self, msg: AudioBatchMessage, plan,
                  per_window) -> List[TranscriptMessage]:
        """Fan per-window tokens back to one TranscriptMessage per ref,
        input order, failures explicit."""
        per_file = self.pipeline.chunker.reassemble(plan, per_window)
        counts = plan.windows_per_file()
        detok = getattr(self.pipeline, "detokenize", None)
        out: List[TranscriptMessage] = []
        for i, ref in enumerate(msg.refs):
            common = dict(crawl_id=msg.crawl_id, batch_id=msg.batch_id,
                          worker_id=self.cfg.worker_id,
                          trace_id=msg.trace_id, tenant=msg.tenant)
            if i in plan.errors:
                out.append(TranscriptMessage.new(
                    ref.media_id, path=ref.path,
                    channel_name=ref.channel_name,
                    error=plan.errors[i], **common))
                continue
            toks = per_file[i]
            text = detok(toks) if callable(detok) else ""
            rate = float(getattr(self.pipeline, "sample_rate", 16_000))
            out.append(TranscriptMessage.new(
                ref.media_id, path=ref.path,
                channel_name=ref.channel_name, text=text, tokens=toks,
                windows=counts[i],
                duration_s=counts[i] * plan.window_samples / rate,
                **common))
        return out

    def _commit(self, msg: AudioBatchMessage,
                transcripts: List[TranscriptMessage]) -> None:
        self.m_batches.inc()
        self.m_media.inc(len(transcripts))
        for t in transcripts:
            self.bus.publish(TOPIC_TRANSCRIPTS, t.to_dict())
        if self.provider is not None:
            self._writeback(msg, transcripts)

    def _writeback(self, msg: AudioBatchMessage,
                   transcripts: List[TranscriptMessage]) -> None:
        """Idempotent: one atomically-written file per batch_id, so a bus
        redelivery or worker restart overwrites the same file with the
        same content instead of duplicating rows."""
        rel = (f"{self.cfg.storage_prefix}/{msg.crawl_id or 'adhoc'}"
               f"/batches/{msg.batch_id}.jsonl")
        lines = []
        for t in transcripts:
            row = {
                "media_id": t.media_id,
                "post_uid": t.post_uid,
                "channel_name": t.channel_name,
                "batch_id": msg.batch_id,
                "trace_id": msg.trace_id,
                "tenant": msg.tenant,
                "text": t.text,
                "windows": t.windows,
                "error": t.error,
            }
            if self.cfg.write_tokens:
                row["tokens"] = list(t.tokens)
            lines.append(json.dumps(row, ensure_ascii=False))
        self.provider.put_text(rel, "\n".join(lines) + "\n")

    def _observe_age(self, msg: AudioBatchMessage) -> None:
        if msg.created_at is None:
            return
        from ..state.datamodels import utcnow

        age = (utcnow() - msg.created_at).total_seconds()
        if age >= 0:
            self.m_batch_age.observe(age)
            # Retroactive span: the whole-pipeline age budget
            # (slo_batch_age) — it covers the broker leg queue_wait
            # can't see, the signal that fires when a killed ASR
            # worker's backlog finally lands.
            trace.record("asr_worker.batch_age", age,
                         trace_id=msg.trace_id, batch=msg.batch_id,
                         worker=self.cfg.worker_id, tenant=msg.tenant)

    # -- heartbeats ----------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._slo.evaluate()
            except Exception as e:  # budget math must never kill the beat
                logger.warning("slo evaluation failed: %s", e)
            status = WORKER_BUSY if not self._queue.empty() else WORKER_IDLE
            msg = StatusMessage.new(
                self.cfg.worker_id, MSG_HEARTBEAT, status,
                tasks_processed=self._processed,
                tasks_success=self._processed - self._errors,
                tasks_error=self._errors,
                uptime_s=time.monotonic() - self._started_at,
                worker_type="asr")
            msg.queue_length = self._queue.qsize()
            msg.resource_usage = self._telemetry.snapshot()
            msg.resource_usage["queue"] = {
                "depth": self._queue.qsize(),
                "depth_time_weighted": round(self._depth.sample(), 4),
            }
            # Burn-rate feed + self-sample, the TPU worker's mirror.
            slo_snap = self._slo.snapshot()
            msg.resource_usage["slo_breaches"] = slo_snap["breaches"]
            if slo_snap.get("tenant_breaches"):
                msg.resource_usage["tenant_slo_breaches"] = \
                    slo_snap["tenant_breaches"]
            ledger = self._tenant_ledger()
            if ledger is not None:
                tenants = ledger.snapshot()
                if tenants["rows"]:
                    msg.resource_usage["tenants"] = tenants
            self._ts_sampler.sample()
            try:
                self.bus.publish(TOPIC_WORKER_STATUS, msg.to_dict())
            except Exception as e:  # bus outage must not kill the worker
                logger.warning("heartbeat publish failed: %s", e)
            self._wait_with_span_exports(self.cfg.heartbeat_s)

    def _wait_with_span_exports(self, wait_s: float) -> None:
        """Sleep until the next heartbeat, firing span exports on their
        OWN cadence in between (the TPU worker's mirror)."""
        deadline = time.monotonic() + wait_s
        interval = self.cfg.span_export_interval_s
        while not self._stop.is_set():
            if interval > 0 and \
                    time.monotonic() - self._last_span_export >= interval:
                self._last_span_export = time.monotonic()
                self.export_spans()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._stop.wait(min(remaining, interval)
                            if interval > 0 else remaining)
