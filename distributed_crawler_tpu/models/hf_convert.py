"""HuggingFace -> Flax parameter conversion for the model zoo.

BASELINE.md's configs name *published* checkpoints (multilingual-E5, XLM-R,
Whisper); this module maps their HF layouts onto the param trees of
`models.encoder` / `models.whisper`, entirely offline (local files only —
the deployment ships checkpoint dirs the same way the reference shipped
pre-seeded TDLib DBs, `telegramhelper/client.go:232-260`).

Supported sources, auto-detected inside the checkpoint dir:
- ``model.safetensors`` (read with safetensors.numpy)
- ``pytorch_model.bin`` (read with torch, CPU map_location)

Layout notes (RoBERTa/XLM-R family — E5 is an XLM-R encoder):
- torch ``nn.Linear.weight`` is [out, in]; Flax ``Dense.kernel`` is
  [in, out] -> transpose.
- RoBERTa position ids start at ``padding_idx + 1 = 2``
  (`modeling_roberta.create_position_ids_from_input_ids`), so rows 0-1 of
  the HF position table are dead for right-padded input -> slice them off.
- token_type embeddings have a single row for these models; every token
  receives row 0 exactly once -> fold it into the position table.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Mapping, Optional

import numpy as np

from .encoder import EncoderConfig

_POS_OFFSET = 2  # RoBERTa: padding_idx (1) + 1


# ---------------------------------------------------------------------------
# State-dict loading (offline, format auto-detect)
# ---------------------------------------------------------------------------

def load_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read an HF checkpoint dir (or a single weight file) into numpy."""
    if os.path.isdir(path):
        st = os.path.join(path, "model.safetensors")
        pt = os.path.join(path, "pytorch_model.bin")
        if os.path.exists(st):
            path = st
        elif os.path.exists(pt):
            path = pt
        else:
            raise FileNotFoundError(
                f"no model.safetensors or pytorch_model.bin under {path}")
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return dict(load_file(path))
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    return {k: v.numpy() for k, v in state.items()}


def load_hf_config(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "config.json"), "r", encoding="utf-8") as f:
        return json.load(f)


def encoder_config_from_hf(hf_cfg: Mapping[str, Any],
                           n_labels: int = 2,
                           dtype: str = "bfloat16") -> EncoderConfig:
    """EncoderConfig matching an HF RoBERTa/XLM-R/BERT config.json."""
    return EncoderConfig(
        vocab_size=int(hf_cfg["vocab_size"]),
        hidden=int(hf_cfg["hidden_size"]),
        n_layers=int(hf_cfg["num_hidden_layers"]),
        n_heads=int(hf_cfg["num_attention_heads"]),
        mlp_dim=int(hf_cfg["intermediate_size"]),
        max_len=int(hf_cfg["max_position_embeddings"]) - _POS_OFFSET,
        layer_norm_eps=float(hf_cfg.get("layer_norm_eps", 1e-5)),
        n_labels=n_labels,
        dtype=dtype,
    )


# ---------------------------------------------------------------------------
# RoBERTa/XLM-R/E5 -> models.encoder
# ---------------------------------------------------------------------------

def _strip_prefix(state: Mapping[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Drop a leading model-name prefix (``roberta.``, ``bert.``) if every
    encoder key carries one (classification checkpoints do)."""
    for prefix in ("roberta.", "bert.", "xlm_roberta.", "model."):
        if any(k.startswith(prefix + "embeddings.") for k in state):
            out = {}
            for k, v in state.items():
                out[k[len(prefix):] if k.startswith(prefix) else k] = v
            return out
    return dict(state)


def _dense(state: Mapping[str, np.ndarray], key: str) -> Dict[str, np.ndarray]:
    return {"kernel": np.ascontiguousarray(state[f"{key}.weight"].T),
            "bias": state[f"{key}.bias"]}


def _ln(state: Mapping[str, np.ndarray], key: str) -> Dict[str, np.ndarray]:
    return {"scale": state[f"{key}.weight"], "bias": state[f"{key}.bias"]}


def convert_roberta_encoder(state: Mapping[str, np.ndarray],
                            cfg: EncoderConfig) -> Dict[str, Any]:
    """HF RoBERTa-family state dict -> the `models.encoder.Encoder` subtree
    (the value of params["params"]["encoder"])."""
    state = _strip_prefix(state)
    pos = state["embeddings.position_embeddings.weight"][_POS_OFFSET:]
    pos = pos[:cfg.max_len].astype(np.float32).copy()
    type_emb = state.get("embeddings.token_type_embeddings.weight")
    if type_emb is not None:
        # Single-type models: every token adds row 0 once -> fold into the
        # position table so the runtime graph stays two-table.
        pos += type_emb[0][None, :]
    tree: Dict[str, Any] = {
        "embed_tokens": state["embeddings.word_embeddings.weight"].astype(
            np.float32),
        "embed_positions": pos,
        "ln_embed": _ln(state, "embeddings.LayerNorm"),
    }
    for i in range(cfg.n_layers):
        base = f"encoder.layer.{i}"
        # The model's attention projection is FUSED (encoder.py
        # SelfAttention: one [h, 3, h] kernel); stack HF's separate
        # query/key/value weights onto the middle axis.
        q = _dense(state, f"{base}.attention.self.query")
        k = _dense(state, f"{base}.attention.self.key")
        v = _dense(state, f"{base}.attention.self.value")
        tree[f"layers_{i}"] = {
            "attn": {
                "qkv/kernel": np.stack(
                    [q["kernel"], k["kernel"], v["kernel"]], axis=1),
                "qkv/bias": np.stack(
                    [q["bias"], k["bias"], v["bias"]], axis=0),
                "attn_out": _dense(state, f"{base}.attention.output.dense"),
            },
            "ln_attn": _ln(state, f"{base}.attention.output.LayerNorm"),
            "mlp": {
                "mlp_up": _dense(state, f"{base}.intermediate.dense"),
                "mlp_down": _dense(state, f"{base}.output.dense"),
            },
            "ln_mlp": _ln(state, f"{base}.output.LayerNorm"),
        }
    return tree


def convert_classification_head(state: Mapping[str, np.ndarray]
                                ) -> Optional[Dict[str, Any]]:
    """HF RobertaClassificationHead (classifier.dense + classifier.out_proj)
    or BERT pooler+classifier -> `ClassificationHead` subtree; None if the
    checkpoint has no head."""
    if "classifier.dense.weight" in state:
        return {"pooler": _dense(state, "classifier.dense"),
                "head": _dense(state, "classifier.out_proj")}
    if "pooler.dense.weight" in state and "classifier.weight" in state:
        return {"pooler": _dense(state, "pooler.dense"),
                "head": _dense(state, "classifier")}
    return None


def load_hf_encoder(path: str, arch: str = "embedder_classifier",
                    n_labels: Optional[int] = None,
                    dtype: str = "bfloat16"):
    """Load an HF RoBERTa/XLM-R/E5 checkpoint dir into (cfg, params).

    ``arch``: "embedder" (E5 pooling), "classifier", or
    "embedder_classifier" (the fused flagship).  Returns params shaped for
    the corresponding `models.encoder` module: ``{"params": {...}}``.
    """
    hf_cfg = load_hf_config(path)
    state = _strip_prefix(load_state_dict(path))
    head = convert_classification_head(state)
    if n_labels is None:
        n_labels = (head["head"]["bias"].shape[0] if head is not None
                    else int(hf_cfg.get("num_labels", 2)))
    cfg = encoder_config_from_hf(hf_cfg, n_labels=n_labels, dtype=dtype)
    encoder = convert_roberta_encoder(state, cfg)
    if arch == "embedder":
        params = {"encoder": encoder}
    else:
        if head is None:
            # Encoder-only checkpoint (E5): init-shaped random head is the
            # caller's job; refuse silently-wrong zeros.
            raise ValueError(
                f"checkpoint at {path} has no classification head; "
                f"load with arch='embedder' or fine-tune a head")
        params = {"encoder": encoder, "cls_head": head}
    return cfg, {"params": params}


# ---------------------------------------------------------------------------
# Whisper -> models.whisper
# ---------------------------------------------------------------------------

def _whisper_attn(state: Mapping[str, np.ndarray],
                  base: str) -> Dict[str, Any]:
    """HF WhisperAttention: k_proj has no bias (matches OpenAI layout and
    `models.whisper._MHA`, whose k Dense is use_bias=False)."""
    return {
        "q": _dense(state, f"{base}.q_proj"),
        "k": {"kernel": np.ascontiguousarray(
            state[f"{base}.k_proj.weight"].T)},
        "v": _dense(state, f"{base}.v_proj"),
        "attn_out": _dense(state, f"{base}.out_proj"),
    }


def whisper_config_from_hf(hf_cfg: Mapping[str, Any]):
    from .whisper import WhisperConfig

    return WhisperConfig(
        n_mels=int(hf_cfg["num_mel_bins"]),
        n_vocab=int(hf_cfg["vocab_size"]),
        n_audio_ctx=int(hf_cfg["max_source_positions"]),
        n_audio_state=int(hf_cfg["d_model"]),
        n_audio_head=int(hf_cfg["encoder_attention_heads"]),
        n_audio_layer=int(hf_cfg["encoder_layers"]),
        n_text_ctx=int(hf_cfg["max_target_positions"]),
        n_text_state=int(hf_cfg["d_model"]),
        n_text_head=int(hf_cfg["decoder_attention_heads"]),
        n_text_layer=int(hf_cfg["decoder_layers"]),
    )


def _conv(state: Mapping[str, np.ndarray], key: str) -> Dict[str, np.ndarray]:
    """torch Conv1d weight [out, in, k] -> flax Conv kernel [k, in, out]."""
    return {"kernel": np.ascontiguousarray(
                state[f"{key}.weight"].transpose(2, 1, 0)),
            "bias": state[f"{key}.bias"]}


def convert_whisper(state: Mapping[str, np.ndarray], cfg) -> Dict[str, Any]:
    """HF WhisperModel/WhisperForConditionalGeneration state dict ->
    `models.whisper.Whisper` param tree (value of params["params"])."""
    s = {}
    for k, v in state.items():
        k = re.sub(r"^(model\.|proj_out\.)", "", k)
        s[k] = v

    def block(base: str, cross: bool) -> Dict[str, Any]:
        out = {
            "attn": _whisper_attn(s, f"{base}.self_attn"),
            "ln_attn": _ln(s, f"{base}.self_attn_layer_norm"),
            "mlp": {"mlp_up": _dense(s, f"{base}.fc1"),
                    "mlp_down": _dense(s, f"{base}.fc2")},
            "ln_mlp": _ln(s, f"{base}.final_layer_norm"),
        }
        if cross:
            out["cross_attn"] = _whisper_attn(s, f"{base}.encoder_attn")
            out["ln_cross"] = _ln(s, f"{base}.encoder_attn_layer_norm")
        return out

    enc: Dict[str, Any] = {
        "conv1": _conv(s, "encoder.conv1"),
        "conv2": _conv(s, "encoder.conv2"),
        "ln_post": _ln(s, "encoder.layer_norm"),
    }
    for i in range(cfg.n_audio_layer):
        enc[f"layers_{i}"] = block(f"encoder.layers.{i}", cross=False)

    dec: Dict[str, Any] = {
        "embed_tokens": s["decoder.embed_tokens.weight"].astype(np.float32),
        "embed_positions": s["decoder.embed_positions.weight"].astype(
            np.float32)[:cfg.n_text_ctx],
        "ln_post": _ln(s, "decoder.layer_norm"),
    }
    for i in range(cfg.n_text_layer):
        dec[f"layers_{i}"] = block(f"decoder.layers.{i}", cross=True)

    return {"encoder": enc, "decoder": dec}


def load_hf_whisper(path: str):
    """Load an HF Whisper checkpoint dir into (cfg, params)."""
    hf_cfg = load_hf_config(path)
    cfg = whisper_config_from_hf(hf_cfg)
    params = convert_whisper(load_state_dict(path), cfg)
    return cfg, {"params": params}
