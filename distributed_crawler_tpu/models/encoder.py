"""Transformer text encoder: the E5/XLM-R family, TPU-first.

Architecture is the standard BERT/RoBERTa encoder (the reference crawls text;
BASELINE.md grafts multilingual-E5 embedding + XLM-R classification onto the
crawl stream).  TPU-first choices:

- bf16 activations / f32 params: matmuls hit the MXU at full rate, layernorm
  and softmax accumulate in f32;
- post-LN like BERT, but residual adds in f32 to keep 24-layer (E5-large)
  numerics stable in bf16;
- attention via `ops.mha`: XLA-fused below 1k tokens, Pallas flash above;
- no dynamic shapes anywhere — padding masks, not ragged lengths;
- optional mixture-of-experts MLP (top-1 switch routing) whose expert dim the
  sharding rules place on the tp axis (expert parallelism);
- parameter names (qkv/attn_out/mlp_up/mlp_down/embed) are the contract
  with `parallel.sharding.ENCODER_PARAM_RULES` — a new projection must get
  a rule there or it silently falls back to replicate-everything.  The
  attention projection is FUSED: one ``qkv/kernel`` [h, 3, h] GEMM (q/k/v
  on the middle axis, heads on the last so tp sharding stays head-aligned).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import mha
from ..ops.quant import (
    int8_dense,
    int8_experts_down,
    int8_experts_up,
    int8_qkv,
)


@dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 250002          # XLM-R sentencepiece vocab
    hidden: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    n_labels: int = 2                 # classifier head width
    n_experts: int = 0                # 0 = dense MLP; >0 = switch MoE
    # Switch-MoE dispatch strategy:
    #   "dense"    — one-hot einsum computes EVERY expert for EVERY token
    #                then selects: exact, no drops, but n_experts× the
    #                MLP FLOPs — right for tiny expert counts and as the
    #                reference semantics for tests;
    #   "capacity" — tokens are routed to at most
    #                ceil(tokens/E * moe_capacity_factor) slots per
    #                expert via static-shape dispatch matmuls (the
    #                Switch-Transformer scheme): ~capacity_factor× the
    #                MLP FLOPs regardless of E; tokens beyond a full
    #                expert's capacity are dropped (contribute zero),
    #                standard switch behavior.  Exact equality with
    #                "dense" whenever nothing overflows.
    moe_dispatch: str = "dense"
    moe_capacity_factor: float = 1.25
    dropout: float = 0.0              # inference-first; training may override
    layer_norm_eps: float = 1e-5
    dtype: str = "bfloat16"           # activation dtype
    attention: str = "auto"           # auto | xla | flash
    remat: bool = False               # jax.checkpoint each layer (training)
    # "int8": the projection GEMMs per layer (qkv/attn_out/mlp, or the MoE
    # expert GEMMs) run int8×int8→int32 on the MXU (2× bf16 peak on v5e,
    # half the weight HBM traffic).  Params must be in the quantized layout
    # (`models/quant.quantize_encoder_params` converts a float checkpoint);
    # serving-only — training always "none".
    # "int8_static": same, with CALIBRATED per-tensor activation scales
    # (`models/quant.calibrate_activation_scales`) instead of dynamic
    # per-token abs-max — the quantize fuses into the producer epilogue,
    # removing one full activation HBM round-trip per projection
    # (`ops/quant.quantize_activations_static`).  MoE experts stay dynamic.
    quant: str = "none"
    # True (with quant="none"): sow per-projection input abs-max into the
    # "calib" collection so `calibrate_activation_scales` can read them.
    calibrate: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden // self.n_heads

    @property
    def adtype(self):
        return jnp.dtype(self.dtype)

    def validate(self) -> None:
        if self.hidden % self.n_heads != 0:
            raise ValueError(
                f"hidden {self.hidden} not divisible by heads {self.n_heads}")
        if self.quant not in ("none", "int8", "int8_static"):
            raise ValueError(f"unknown quant mode {self.quant!r}")
        if self.moe_dispatch not in ("dense", "capacity"):
            raise ValueError(
                f"unknown moe_dispatch {self.moe_dispatch!r}")
        if self.moe_dispatch == "capacity" and self.quant != "none":
            raise ValueError(
                "moe_dispatch='capacity' requires quant='none' — the "
                "int8 expert GEMMs' per-expert quantized layout can't "
                "host the pack/unpack matmuls; use dense dispatch")
        if self.calibrate and self.quant != "none":
            raise ValueError("calibrate requires the float path "
                             "(quant='none')")


# Published configs (sizes match the HF checkpoints these mirror).
E5_SMALL = EncoderConfig(vocab_size=250037, hidden=384, n_layers=12,
                         n_heads=12, mlp_dim=1536)
E5_BASE = EncoderConfig(vocab_size=250037, hidden=768, n_layers=12,
                        n_heads=12, mlp_dim=3072)
E5_LARGE = EncoderConfig(vocab_size=250037, hidden=1024, n_layers=24,
                         n_heads=16, mlp_dim=4096)
XLMR_BASE = EncoderConfig(vocab_size=250002, hidden=768, n_layers=12,
                          n_heads=12, mlp_dim=3072)
# Tiny config for tests: runs on the 8-device CPU mesh in milliseconds.
TINY_TEST = EncoderConfig(vocab_size=1024, hidden=64, n_layers=2, n_heads=4,
                          mlp_dim=128, max_len=128, dtype="float32")


class QuantDense(nn.Module):
    """Int8 drop-in for the projection `nn.Dense`s (serving only).

    Param layout: ``kernel_q`` int8 [in, out] + ``scale`` f32 [out] +
    ``bias`` f32 [out] — produced from a float checkpoint by
    `models/quant.quantize_encoder_params`, never trained directly (the
    zeros/ones initializers only exist so `.init()` yields the right
    shapes for shape-driven code paths).  In ``int8_static`` configs an
    ``a_scale`` scalar (calibrated activation scale) joins the layout."""

    features: int
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        w_q = self.param("kernel_q", nn.initializers.zeros,
                         (in_dim, self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        a_scale = None
        if self.cfg.quant == "int8_static":
            a_scale = self.param("a_scale", nn.initializers.ones,
                                 (), jnp.float32)
        return int8_dense(x, w_q, scale, bias, out_dtype=self.cfg.adtype,
                          a_scale=a_scale)


def _proj(cfg: EncoderConfig, features: int, name: str):
    """Projection layer: bf16 `nn.Dense` or its int8 twin, same name so
    the sharding rules and checkpoint paths stay stable."""
    if cfg.quant in ("int8", "int8_static"):
        return QuantDense(features, cfg, name=name)
    return nn.Dense(features, dtype=cfg.adtype, param_dtype=jnp.float32,
                    name=name)


def _sow_absmax(module: nn.Module, cfg: EncoderConfig, name: str, x):
    """Calibration hook: record the projection input's abs-max in the
    "calib" collection (reduced with max across calls/batches) under
    ``<projection>_in`` — suffixed because a sow name may not collide
    with a submodule name in flax's namespace."""
    if cfg.calibrate:
        module.sow("calib", f"{name}_in",
                   jnp.max(jnp.abs(x.astype(jnp.float32))),
                   reduce_fn=jnp.maximum,
                   init_fn=lambda: jnp.float32(0))


class SelfAttention(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x, mask, segment_ids=None):
        cfg = self.cfg
        b, l, _ = x.shape
        # Fused QKV: one [h, 3, h] GEMM instead of three [h, h] GEMMs — at
        # encoder widths (384-1024) the separate projections underfill the
        # 128x128 MXU tiles; the kernel keeps q/k/v on a dedicated axis so
        # tp-sharding the LAST axis stays head-aligned (no projection is
        # ever split across devices).
        if cfg.quant in ("int8", "int8_static"):
            w_q = self.param("qkv/kernel_q", nn.initializers.zeros,
                             (cfg.hidden, 3, cfg.hidden), jnp.int8)
            scale = self.param("qkv/scale", nn.initializers.ones,
                               (3, cfg.hidden), jnp.float32)
            bias = self.param("qkv/bias", nn.initializers.zeros,
                              (3, cfg.hidden), jnp.float32)
            a_scale = None
            if cfg.quant == "int8_static":
                a_scale = self.param("qkv/a_scale", nn.initializers.ones,
                                     (), jnp.float32)
            proj = int8_qkv(x, w_q, scale, bias, out_dtype=cfg.adtype,
                            a_scale=a_scale)
        else:
            _sow_absmax(self, cfg, "qkv", x)
            w = self.param(
                "qkv/kernel",
                nn.initializers.variance_scaling(1.0, "fan_in",
                                                 "truncated_normal",
                                                 in_axis=0, out_axis=(1, 2)),
                (cfg.hidden, 3, cfg.hidden), jnp.float32)
            bias = self.param("qkv/bias", nn.initializers.zeros,
                              (3, cfg.hidden), jnp.float32)
            proj = jnp.einsum("blh,hto->blto", x.astype(cfg.adtype),
                              w.astype(cfg.adtype)) + bias.astype(cfg.adtype)
        q = proj[:, :, 0].reshape(b, l, cfg.n_heads, cfg.head_dim)
        k = proj[:, :, 1].reshape(b, l, cfg.n_heads, cfg.head_dim)
        v = proj[:, :, 2].reshape(b, l, cfg.n_heads, cfg.head_dim)
        use_flash = {"auto": None, "xla": False, "flash": True}[cfg.attention]
        o = mha(q, k, v, kv_mask=mask, use_flash=use_flash,
                segment_ids=segment_ids)
        o = o.reshape(b, l, cfg.hidden)
        _sow_absmax(self, cfg, "attn_out", o)
        return _proj(cfg, cfg.hidden, "attn_out")(o)


class DenseMLP(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        _sow_absmax(self, cfg, "mlp_up", x)
        h = _proj(cfg, cfg.mlp_dim, "mlp_up")(x)
        # Exact (erf) GELU: parity with published BERT/RoBERTa checkpoints;
        # XLA fuses erf into the matmul epilogue so tanh-approx buys nothing.
        h = nn.gelu(h, approximate=False)
        _sow_absmax(self, cfg, "mlp_down", h)
        return _proj(cfg, cfg.hidden, "mlp_down")(h)


class SwitchMoE(nn.Module):
    """Top-1 switch MLP with selectable dispatch (cfg.moe_dispatch).

    "dense": one-hot einsum computes every expert for every token then
    selects — exact, no drops, n_experts× the MLP FLOPs; the reference
    semantics for tests and the int8 expert path.

    "capacity": the Switch-Transformer scheme — tokens are packed into
    ceil(group/E * capacity_factor) static slots per expert with
    dispatch/combine matmuls, grouped along the token axis so the
    [group, E, capacity] dispatch tensor's HBM footprint is bounded per
    group instead of scaling with the whole batch.  ~capacity_factor×
    the MLP FLOPs regardless of E; overflow tokens are dropped
    (contribute zero); attention-padding tokens are excluded from
    routing so they can't evict real tokens from capacity.

    Either way XLA shards the expert dim over tp per the param rules.
    """

    cfg: EncoderConfig

    # Token-group size for capacity dispatch: grouping bounds the
    # [group, E, capacity] dispatch tensor's HBM footprint (and the
    # pack/unpack matmul tile sizes) at ~group²·cf elements — ~42 MB in
    # bf16 at 4096 — instead of letting it scale with the whole batch.
    _GROUP = 4096

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.cfg
        e = cfg.n_experts
        gate = nn.Dense(e, dtype=jnp.float32, param_dtype=jnp.float32,
                        name="router")(x.astype(jnp.float32))
        probs = jax.nn.softmax(gate, axis=-1)           # [B, L, E]
        top = jnp.argmax(probs, axis=-1)                # [B, L]
        # Switch load-balancing auxiliary loss (sowed into the "losses"
        # collection; a no-op unless the caller makes it mutable — the
        # full-fine-tune train step does, inference never):
        # aux = E · Σ_e f_e·P_e with f_e the dispatched-token fraction
        # and P_e the mean router prob, over REAL tokens only.  ≈1 when
        # balanced, →E when the router collapses onto one expert.
        if not self.is_initializing():
            # Guarded: init() runs with every collection mutable, and an
            # init-time sow would bake a stale value into the variables
            # dict that later applies reduce ONTO.
            w = (jnp.ones(top.shape, jnp.float32) if mask is None
                 else mask.astype(jnp.float32))
            denom = jnp.maximum(jnp.sum(w), 1.0)
            p_e = jnp.sum(probs * w[..., None], axis=(0, 1)) / denom
            f_e = jnp.sum(jax.nn.one_hot(top, e) * w[..., None],
                          axis=(0, 1)) / denom
            self.sow("losses", "moe_aux", e * jnp.sum(f_e * p_e),
                     reduce_fn=jnp.add, init_fn=lambda: jnp.float32(0))
        if cfg.moe_dispatch == "capacity":
            # validate() guarantees quant == "none" here; int8 expert
            # GEMMs ride the dense dispatch (their per-expert quantized
            # layout can't host the pack/unpack matmuls).
            out = self._capacity_experts(x, top, mask)
        else:
            out = self._dense_experts(x, top)
        # Scale by the (f32) router prob of the chosen expert so the router
        # receives gradient during fine-tuning.
        chosen = jnp.sum(probs * jax.nn.one_hot(top, e), axis=-1)
        return out * chosen[..., None].astype(cfg.adtype)

    def _expert_params(self):
        cfg = self.cfg
        e, h, m = cfg.n_experts, cfg.hidden, cfg.mlp_dim
        w_up = self.param("experts_up/kernel",
                          nn.initializers.lecun_normal(),
                          (e, h, m), jnp.float32)
        w_dn = self.param("experts_down/kernel",
                          nn.initializers.lecun_normal(),
                          (e, m, h), jnp.float32)
        return w_up, w_dn

    def _dense_experts(self, x, top):
        cfg = self.cfg
        e = cfg.n_experts
        onehot = jax.nn.one_hot(top, e, dtype=cfg.adtype)
        # int8_static uses the DYNAMIC expert path: per-(token, expert)
        # activation stats vary too much for one static scale, and the
        # expert GEMMs' dispatch einsum can't host the fused quantize
        # anyway.
        if cfg.quant in ("int8", "int8_static"):
            h, m = cfg.hidden, cfg.mlp_dim
            w_up_q = self.param("experts_up/kernel_q", nn.initializers.zeros,
                                (e, h, m), jnp.int8)
            s_up = self.param("experts_up/scale", nn.initializers.ones,
                              (e, m), jnp.float32)
            w_dn_q = self.param("experts_down/kernel_q",
                                nn.initializers.zeros, (e, m, h), jnp.int8)
            s_dn = self.param("experts_down/scale", nn.initializers.ones,
                              (e, h), jnp.float32)
            hid = int8_experts_up(x, w_up_q, s_up, out_dtype=cfg.adtype)
            hid = nn.gelu(hid, approximate=True)
            out = int8_experts_down(hid, w_dn_q, s_dn, out_dtype=cfg.adtype)
        else:
            w_up, w_dn = self._expert_params()
            hid = jnp.einsum("blh,ehm->blem", x, w_up.astype(cfg.adtype))
            hid = nn.gelu(hid, approximate=True)
            out = jnp.einsum("blem,emh->bleh", hid, w_dn.astype(cfg.adtype))
        return jnp.einsum("bleh,ble->blh", out, onehot)

    def _capacity_experts(self, x, top, mask):
        cfg = self.cfg
        e, h = cfg.n_experts, cfg.hidden
        w_up, w_dn = self._expert_params()
        b, l, _ = x.shape
        n = b * l
        g = min(n, self._GROUP)
        n_pad = int(math.ceil(n / g)) * g
        cap = max(1, int(math.ceil(g / e * cfg.moe_capacity_factor)))
        xf = x.reshape(n, h)
        topf = top.reshape(n)
        # Attention-padding tokens must not route: they'd consume
        # capacity and evict REAL tokens arriving later in the group
        # (their MLP output is masked out downstream anyway).
        valid = (jnp.ones(n, bool) if mask is None
                 else mask.reshape(n).astype(bool))
        if n_pad != n:
            xf = jnp.pad(xf, ((0, n_pad - n), (0, 0)))
            topf = jnp.pad(topf, (0, n_pad - n))
            valid = jnp.pad(valid, (0, n_pad - n))
        onehot = (jax.nn.one_hot(topf, e, dtype=jnp.int32)
                  * valid[:, None].astype(jnp.int32))       # [N, E]
        k = n_pad // g
        oh_g = onehot.reshape(k, g, e)
        # 0-based arrival position of each token within its expert's
        # per-group queue; >= cap beyond capacity (dropped).
        pos = jnp.cumsum(oh_g, axis=1) * oh_g - oh_g        # [K, G, E]
        keep = ((pos < cap) & (oh_g > 0)).astype(cfg.adtype)
        disp = (jax.nn.one_hot(pos, cap, dtype=cfg.adtype)
                * keep[..., None])                          # [K, G, E, C]
        xg = xf.reshape(k, g, h).astype(cfg.adtype)
        x_e = jnp.einsum("kgec,kgh->kech", disp, xg)        # pack
        hid = jnp.einsum("kech,ehm->kecm", x_e, w_up.astype(cfg.adtype))
        hid = nn.gelu(hid, approximate=True)
        out_e = jnp.einsum("kecm,emh->kech", hid, w_dn.astype(cfg.adtype))
        y = jnp.einsum("kgec,kech->kgh", disp, out_e)       # unpack
        return y.reshape(n_pad, h)[:n].reshape(b, l, h)


class EncoderLayer(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x, mask, segment_ids=None):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
            param_dtype=jnp.float32, name=name)
        a = SelfAttention(cfg, name="attn")(x, mask, segment_ids)
        x = ln("ln_attn")(x.astype(jnp.float32)
                          + a.astype(jnp.float32)).astype(cfg.adtype)
        if cfg.n_experts:
            m = SwitchMoE(cfg, name="moe")(x, mask=mask)
        else:
            m = DenseMLP(cfg, name="mlp")(x)
        x = ln("ln_mlp")(x.astype(jnp.float32)
                         + m.astype(jnp.float32)).astype(cfg.adtype)
        return x


class Encoder(nn.Module):
    """ids [B, L] int32, mask [B, L] bool -> hidden [B, L, H] (cfg dtype).

    Packed rows (`ops/padding.pack_rows`) additionally pass ``segment_ids``
    [B, L] int32 (attention is confined per segment) and ``positions``
    [B, L] int32 (within-segment offsets, so every packed sequence sees the
    same absolute position embeddings as its unpacked twin)."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids, mask, segment_ids=None, positions=None):
        cfg = self.cfg
        cfg.validate()
        emb = self.param("embed_tokens", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.hidden), jnp.float32)
        pos = self.param("embed_positions", nn.initializers.normal(0.02),
                         (cfg.max_len, cfg.hidden), jnp.float32)
        l = ids.shape[1]
        if positions is not None:
            x = emb[ids] + pos[positions]
        else:
            x = emb[ids] + pos[:l][None, :, :]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                         param_dtype=jnp.float32, name="ln_embed")(x)
        x = x.astype(cfg.adtype)
        layer_cls = EncoderLayer
        if cfg.remat:
            layer_cls = nn.remat(EncoderLayer, static_argnums=())
        for i in range(cfg.n_layers):
            x = layer_cls(cfg, name=f"layers_{i}")(x, mask, segment_ids)
        return x


def mean_pool(hidden: jax.Array, mask: jax.Array) -> jax.Array:
    """Masked mean over seq (E5 pooling), f32 accumulation."""
    m = mask[..., None].astype(jnp.float32)
    summed = jnp.sum(hidden.astype(jnp.float32) * m, axis=1)
    count = jnp.maximum(jnp.sum(m, axis=1), 1.0)
    return summed / count


def l2_normalize(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), eps)


def _segment_onehot(mask: jax.Array, segment_ids: jax.Array,
                    n_segments: int) -> jax.Array:
    """[B, L, S] f32 membership: token l of row b belongs to segment s+1."""
    sel = (segment_ids[:, :, None] ==
           jnp.arange(1, n_segments + 1, dtype=segment_ids.dtype)[None, None])
    return (sel & mask[:, :, None]).astype(jnp.float32)


def segment_mean_pool(hidden: jax.Array, mask: jax.Array,
                      segment_ids: jax.Array, n_segments: int) -> jax.Array:
    """Per-segment masked mean over packed rows: [B, L, H] -> [B, S, H].

    Tokens outside a segment enter its sum with an exactly-zero weight, so
    a segment's pooled vector is bit-for-bit independent of its packed
    neighbors; empty slots pool to zero (count clamped to 1)."""
    sel = _segment_onehot(mask, segment_ids, n_segments)
    summed = jnp.einsum("blh,bls->bsh", hidden.astype(jnp.float32), sel)
    count = jnp.maximum(jnp.sum(sel, axis=1), 1.0)
    return summed / count[..., None]


def segment_first_token(hidden: jax.Array, mask: jax.Array,
                        segment_ids: jax.Array,
                        n_segments: int) -> jax.Array:
    """Each segment's first-token state: [B, L, H] -> [B, S, H] — the
    per-segment CLS analog (the packer lays every sequence down CLS-first).
    Empty slots select nothing and come out zero."""
    sel = _segment_onehot(mask, segment_ids, n_segments)
    first = sel * (jnp.cumsum(sel, axis=1) == 1.0)
    return jnp.einsum("blh,bls->bsh", hidden.astype(jnp.float32), first)


class ClassificationHead(nn.Module):
    """XLM-R-style head: first-token state -> tanh dense -> logits (f32).
    Shared by Classifier and EmbedderClassifier so the fused benchmark model
    cannot drift from the standalone one."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, cls_state):
        cfg = self.cfg
        pooled = jnp.tanh(nn.Dense(cfg.hidden, dtype=jnp.float32,
                                   param_dtype=jnp.float32,
                                   name="pooler")(cls_state.astype(jnp.float32)))
        return nn.Dense(cfg.n_labels, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(pooled)


class Embedder(nn.Module):
    """E5-style sentence embedder: encoder -> masked mean -> L2 normalize.
    Returns f32 [B, H] unit vectors."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids, mask):
        hidden = Encoder(self.cfg, name="encoder")(ids, mask)
        return l2_normalize(mean_pool(hidden, mask))


class Classifier(nn.Module):
    """XLM-R-style classifier: encoder -> head -> logits f32 [B, n_labels]."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids, mask):
        hidden = Encoder(self.cfg, name="encoder")(ids, mask)
        return ClassificationHead(self.cfg, name="cls_head")(hidden[:, 0, :])


class EmbedderClassifier(nn.Module):
    """Fused single-pass embed+classify — the BASELINE headline op runs one
    encoder, not two, when both outputs are wanted on the same text.

    Packed mode (``segment_ids``/``positions`` from `ops/padding.pack_rows`,
    ``n_segments`` static): one bucket row carries several sequences, and
    the outputs become per-SEGMENT — emb [B, S, H], logits [B, S, n_labels]
    — each segment mean-pooled over its own tokens and classified from its
    own first (CLS) token, never blended with packed neighbors.  The param
    tree is identical in both modes, so one checkpoint serves both."""

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids, mask, segment_ids=None, positions=None,
                 n_segments: int = 0):
        hidden = Encoder(self.cfg, name="encoder")(ids, mask,
                                                   segment_ids, positions)
        if segment_ids is None:
            emb = l2_normalize(mean_pool(hidden, mask))
            logits = ClassificationHead(self.cfg, name="cls_head")(
                hidden[:, 0, :])
            return emb, logits
        if n_segments <= 0:
            raise ValueError("packed mode requires n_segments > 0")
        emb = l2_normalize(
            segment_mean_pool(hidden, mask, segment_ids, n_segments))
        cls_states = segment_first_token(hidden, mask, segment_ids,
                                         n_segments)
        logits = ClassificationHead(self.cfg, name="cls_head")(cls_states)
        return emb, logits
