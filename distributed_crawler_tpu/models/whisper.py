"""Whisper-family ASR: log-mel frontend, audio encoder, KV-cached decoder.

BASELINE.md config #4 runs Whisper-small over Telegram voice/video media.
TPU-first choices, consistent with `models/encoder.py`:

- bf16 activations / f32 params; layernorm + softmax in f32;
- static shapes only: audio is padded/trimmed to 30 s (3000 mel frames),
  decoding runs a fixed-length `lax.scan` with an explicit KV cache carried
  as a pytree (no dynamic shapes, no Python control flow in the loop);
- the mel filterbank and sinusoidal positions are precomputed as numpy
  constants, baked into the jaxpr at trace time;
- cross-attention K/V are computed once per utterance before the decode
  loop (encoder output is static), so each decode step is pure MXU matmuls
  against cached tensors;
- greedy decode early-exits logically via a `finished` flag (tokens after
  EOT are overwritten with EOT) — the scan length is static, which XLA
  prefers over a data-dependent while_loop on TPU.

Parameter naming follows the same q/k/v/attn_out/mlp_up/mlp_down contract as
the text encoder so `parallel.sharding.ENCODER_PARAM_RULES` shard rules
apply unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Configs (sizes mirror the published Whisper checkpoints)
# ---------------------------------------------------------------------------

SAMPLE_RATE = 16_000
N_FFT = 400
HOP_LENGTH = 160
CHUNK_SECONDS = 30
N_SAMPLES = SAMPLE_RATE * CHUNK_SECONDS          # 480_000
N_FRAMES = N_SAMPLES // HOP_LENGTH               # 3000


@dataclass(frozen=True)
class WhisperConfig:
    n_mels: int = 80
    n_vocab: int = 51_865
    n_audio_ctx: int = 1500          # mel frames / 2 (conv stride)
    n_audio_state: int = 768
    n_audio_head: int = 12
    n_audio_layer: int = 12
    n_text_ctx: int = 448
    n_text_state: int = 768
    n_text_head: int = 12
    n_text_layer: int = 12
    dtype: str = "bfloat16"
    # Special tokens (multilingual vocab layout).
    sot_token: int = 50_258          # <|startoftranscript|>
    eot_token: int = 50_257          # <|endoftext|>
    no_timestamps_token: int = 50_363
    transcribe_token: int = 50_359

    @property
    def adtype(self):
        return jnp.dtype(self.dtype)

    @property
    def audio_head_dim(self) -> int:
        return self.n_audio_state // self.n_audio_head

    @property
    def text_head_dim(self) -> int:
        return self.n_text_state // self.n_text_head


WHISPER_TINY = WhisperConfig(n_audio_state=384, n_audio_head=6,
                             n_audio_layer=4, n_text_state=384,
                             n_text_head=6, n_text_layer=4)
WHISPER_BASE = WhisperConfig(n_audio_state=512, n_audio_head=8,
                             n_audio_layer=6, n_text_state=512,
                             n_text_head=8, n_text_layer=6)
WHISPER_SMALL = WhisperConfig()  # 768/12/12 — BASELINE config #4
# Test config: tiny everything, short audio context, f32 on CPU.
WHISPER_TEST = WhisperConfig(n_mels=8, n_vocab=128, n_audio_ctx=16,
                             n_audio_state=32, n_audio_head=4,
                             n_audio_layer=2, n_text_ctx=12, n_text_state=32,
                             n_text_head=4, n_text_layer=2, dtype="float32",
                             sot_token=1, eot_token=2, no_timestamps_token=3,
                             transcribe_token=4)


# ---------------------------------------------------------------------------
# Log-mel frontend
# ---------------------------------------------------------------------------

def _mel_filterbank(n_mels: int, n_fft: int = N_FFT,
                    sample_rate: int = SAMPLE_RATE) -> np.ndarray:
    """Slaney-scale triangular mel filterbank [n_mels, n_fft//2+1] (numpy:
    computed once at trace time, a compile-time constant on device).

    Matches ``librosa.filters.mel`` defaults (htk=False, norm="slaney") —
    the filterbank published Whisper checkpoints were trained with: the mel
    scale is LINEAR below 1 kHz and logarithmic above, not the HTK
    2595·log10(1+f/700) curve.
    """
    f_sp = 200.0 / 3.0            # Hz per mel in the linear region
    min_log_hz = 1000.0           # linear/log crossover
    min_log_mel = min_log_hz / f_sp
    logstep = np.log(6.4) / 27.0  # step above the crossover

    def hz_to_mel(f):
        f = np.asarray(f, dtype=np.float64)
        return np.where(f < min_log_hz, f / f_sp,
                        min_log_mel + np.log(np.maximum(f, min_log_hz)
                                             / min_log_hz) / logstep)

    def mel_to_hz(m):
        m = np.asarray(m, dtype=np.float64)
        return np.where(m < min_log_mel, m * f_sp,
                        min_log_hz * np.exp(logstep * (m - min_log_mel)))

    n_freqs = n_fft // 2 + 1
    freqs = np.linspace(0, sample_rate / 2, n_freqs)
    mel_pts = np.linspace(hz_to_mel(0.0), hz_to_mel(sample_rate / 2),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts)
    bank = np.zeros((n_mels, n_freqs), dtype=np.float32)
    for i in range(n_mels):
        lo, ctr, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - freqs) / max(hi - ctr, 1e-10)
        bank[i] = np.maximum(0.0, np.minimum(up, down))
    # Slaney area normalization.
    enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
    bank *= enorm[:, None]
    return bank


def pad_or_trim(audio: jax.Array, n_samples: int = N_SAMPLES) -> jax.Array:
    """Fixed 30 s window: trim or zero-pad (static output shape)."""
    length = audio.shape[-1]
    if length > n_samples:
        return audio[..., :n_samples]
    if length < n_samples:
        pad = [(0, 0)] * (audio.ndim - 1) + [(0, n_samples - length)]
        return jnp.pad(audio, pad)
    return audio


def log_mel_spectrogram(audio: jax.Array, n_mels: int = 80,
                        n_fft: int = N_FFT,
                        hop: int = HOP_LENGTH) -> jax.Array:
    """waveform [.., T] (f32, 16 kHz) -> log-mel [.., n_frames, n_mels].

    Hann STFT -> power -> mel -> log10 with Whisper's dynamic-range
    compression.  All ops are XLA-friendly (rfft + matmul on the MXU)."""
    audio = audio.astype(jnp.float32)
    window = jnp.asarray(np.hanning(n_fft + 1)[:-1].astype(np.float32))
    # Reflect-pad so frame centers align with hops (Whisper/librosa layout).
    pad = n_fft // 2
    x = jnp.pad(audio, [(0, 0)] * (audio.ndim - 1) + [(pad, pad)],
                mode="reflect")
    n_frames = audio.shape[-1] // hop
    starts = np.arange(n_frames) * hop
    idx = starts[:, None] + np.arange(n_fft)[None, :]
    frames = x[..., idx] * window                       # [.., F, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1)
    power = jnp.abs(spec) ** 2                          # [.., F, n_fft/2+1]
    mel = jnp.asarray(_mel_filterbank(n_mels, n_fft))
    mspec = jnp.einsum("...fk,mk->...fm", power, mel)
    log_spec = jnp.log10(jnp.maximum(mspec, 1e-10))
    log_spec = jnp.maximum(log_spec,
                           jnp.max(log_spec, axis=(-2, -1), keepdims=True)
                           - 8.0)
    return (log_spec + 4.0) / 4.0


# ---------------------------------------------------------------------------
# Attention building blocks
# ---------------------------------------------------------------------------

def _sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper's fixed sinusoidal positions [length, channels]."""
    log_timescale = np.log(10_000) / (channels // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)],
                          axis=1).astype(np.float32)


def _attend(q, k, v, mask=None):
    """Softmax attention, f32 accumulation.  q [B,Tq,H,D], k/v [B,Tk,H,D];
    mask broadcastable to [B,H,Tq,Tk] (True = attend)."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.float32(-1e30))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class _MHA(nn.Module):
    """Projection block; Whisper has no bias on the key projection."""

    n_state: int
    n_head: int
    dtype: Any

    def setup(self):
        d = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32)
        self.q = d(self.n_state, name="q")
        self.k = d(self.n_state, use_bias=False, name="k")
        self.v = d(self.n_state, name="v")
        self.out = d(self.n_state, name="attn_out")

    def _split(self, x):
        b, t, _ = x.shape
        return x.reshape(b, t, self.n_head, self.n_state // self.n_head)

    def __call__(self, x, xa=None, mask=None):
        """Full-sequence attention (self if xa None, else cross)."""
        src = x if xa is None else xa
        q = self._split(self.q(x))
        k = self._split(self.k(src))
        v = self._split(self.v(src))
        o = _attend(q, k, v, mask)
        return self.out(o.reshape(x.shape))

    def project_kv(self, xa):
        """Precompute cross-attention K/V once per utterance."""
        return self._split(self.k(xa)), self._split(self.v(xa))

    def step(self, x_t, cache_k, cache_v, pos, cross_kv=None):
        """One decode step.  x_t [B,1,S]; self-attn K/V live in fixed-size
        cache buffers updated at `pos` via dynamic_update_slice."""
        q = self._split(self.q(x_t))
        if cross_kv is not None:
            k, v = cross_kv
            o = _attend(q, k, v)
            return self.out(o.reshape(x_t.shape)), cache_k, cache_v
        k_t = self._split(self.k(x_t))
        v_t = self._split(self.v(x_t))
        cache_k = jax.lax.dynamic_update_slice(cache_k, k_t, (0, pos, 0, 0))
        cache_v = jax.lax.dynamic_update_slice(cache_v, v_t, (0, pos, 0, 0))
        # Causal: only positions <= pos are valid.
        t = cache_k.shape[1]
        mask = (jnp.arange(t) <= pos)[None, None, None, :]
        o = _attend(q, cache_k, cache_v, mask)
        return self.out(o.reshape(x_t.shape)), cache_k, cache_v


class _MLP(nn.Module):
    n_state: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(4 * self.n_state, dtype=self.dtype,
                     param_dtype=jnp.float32, name="mlp_up")(x)
        # Exact GELU: parity with published Whisper weights (OpenAI nn.GELU).
        h = nn.gelu(h, approximate=False)
        return nn.Dense(self.n_state, dtype=self.dtype,
                        param_dtype=jnp.float32, name="mlp_down")(h)


def _ln(name):
    return nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                        param_dtype=jnp.float32, name=name)


# ---------------------------------------------------------------------------
# Audio encoder
# ---------------------------------------------------------------------------

class AudioEncoderLayer(nn.Module):
    cfg: WhisperConfig

    def setup(self):
        c = self.cfg
        self.attn = _MHA(c.n_audio_state, c.n_audio_head, c.adtype,
                         name="attn")
        self.mlp = _MLP(c.n_audio_state, c.adtype, name="mlp")
        self.ln_attn = _ln("ln_attn")
        self.ln_mlp = _ln("ln_mlp")

    def __call__(self, x):
        # Pre-LN (Whisper layout); residual adds in f32.
        a = self.attn(self.ln_attn(x.astype(jnp.float32))
                      .astype(self.cfg.adtype))
        x = (x.astype(jnp.float32) + a.astype(jnp.float32))
        m = self.mlp(self.ln_mlp(x).astype(self.cfg.adtype))
        return (x + m.astype(jnp.float32)).astype(self.cfg.adtype)


class AudioEncoder(nn.Module):
    """mel [B, n_frames, n_mels] -> audio features [B, n_audio_ctx, S]."""

    cfg: WhisperConfig

    @nn.compact
    def __call__(self, mel):
        c = self.cfg
        conv = partial(nn.Conv, features=c.n_audio_state, kernel_size=(3,),
                       dtype=c.adtype, param_dtype=jnp.float32)
        x = nn.gelu(conv(strides=(1,), name="conv1")(mel.astype(c.adtype)),
                    approximate=False)
        x = nn.gelu(conv(strides=(2,), name="conv2")(x), approximate=False)
        pos = jnp.asarray(_sinusoids(c.n_audio_ctx, c.n_audio_state))
        x = x + pos[None, :x.shape[1], :].astype(c.adtype)
        for i in range(c.n_audio_layer):
            x = AudioEncoderLayer(c, name=f"layers_{i}")(x)
        x = _ln("ln_post")(x.astype(jnp.float32))
        return x.astype(c.adtype)


# ---------------------------------------------------------------------------
# Text decoder with explicit KV cache
# ---------------------------------------------------------------------------

class DecoderLayer(nn.Module):
    cfg: WhisperConfig

    def setup(self):
        c = self.cfg
        self.self_attn = _MHA(c.n_text_state, c.n_text_head, c.adtype,
                              name="attn")
        self.cross_attn = _MHA(c.n_text_state, c.n_text_head, c.adtype,
                               name="cross_attn")
        self.mlp = _MLP(c.n_text_state, c.adtype, name="mlp")
        self.ln_attn = _ln("ln_attn")
        self.ln_cross = _ln("ln_cross")
        self.ln_mlp = _ln("ln_mlp")

    def _adt(self, x):
        return x.astype(self.cfg.adtype)

    def __call__(self, x, xa, causal_mask):
        """Teacher-forcing full-sequence pass (training / scoring)."""
        a = self.self_attn(self._adt(self.ln_attn(x.astype(jnp.float32))),
                           mask=causal_mask)
        x = x.astype(jnp.float32) + a.astype(jnp.float32)
        ca = self.cross_attn(self._adt(self.ln_cross(x)), xa=xa)
        x = x + ca.astype(jnp.float32)
        m = self.mlp(self._adt(self.ln_mlp(x)))
        return self._adt(x + m.astype(jnp.float32))

    def step(self, x_t, cache, pos, cross_kv):
        a, ck, cv = self.self_attn.step(
            self._adt(self.ln_attn(x_t.astype(jnp.float32))),
            cache["k"], cache["v"], pos)
        x = x_t.astype(jnp.float32) + a.astype(jnp.float32)
        ca, _, _ = self.cross_attn.step(self._adt(self.ln_cross(x)),
                                        None, None, pos, cross_kv=cross_kv)
        x = x + ca.astype(jnp.float32)
        m = self.mlp(self._adt(self.ln_mlp(x)))
        return self._adt(x + m.astype(jnp.float32)), {"k": ck, "v": cv}

    def project_cross_kv(self, xa):
        return self.cross_attn.project_kv(xa)


class TextDecoder(nn.Module):
    cfg: WhisperConfig

    def setup(self):
        c = self.cfg
        self.embed_tokens = self.param("embed_tokens",
                                       nn.initializers.normal(0.02),
                                       (c.n_vocab, c.n_text_state),
                                       jnp.float32)
        self.embed_positions = self.param("embed_positions",
                                          nn.initializers.normal(0.02),
                                          (c.n_text_ctx, c.n_text_state),
                                          jnp.float32)
        self.layers = [DecoderLayer(c, name=f"layers_{i}")
                       for i in range(c.n_text_layer)]
        self.ln_post = _ln("ln_post")

    def _logits(self, x):
        # Tied embedding projection, f32.
        x = self.ln_post(x.astype(jnp.float32))
        return jnp.einsum("btd,vd->btv", x, self.embed_tokens)

    def __call__(self, tokens, xa):
        """Teacher forcing: tokens [B, T] -> logits [B, T, V]."""
        c = self.cfg
        t = tokens.shape[1]
        x = self.embed_tokens[tokens] + self.embed_positions[:t][None]
        x = x.astype(c.adtype)
        causal = jnp.tril(jnp.ones((t, t), bool))[None, None]
        for layer in self.layers:
            x = layer(x, xa, causal)
        return self._logits(x)

    def init_cache(self, batch: int) -> Any:
        c = self.cfg
        shape = (batch, c.n_text_ctx, c.n_text_head, c.text_head_dim)
        return [{"k": jnp.zeros(shape, c.adtype),
                 "v": jnp.zeros(shape, c.adtype)}
                for _ in range(c.n_text_layer)]

    def cross_kv(self, xa):
        return [layer.project_cross_kv(xa) for layer in self.layers]

    def step(self, token_t, pos, cache, cross_kvs):
        """token_t [B, 1] at position pos -> (logits [B, V], new cache)."""
        c = self.cfg
        x = (self.embed_tokens[token_t]
             + jax.lax.dynamic_slice_in_dim(self.embed_positions, pos, 1,
                                            axis=0)[None])
        x = x.astype(c.adtype)
        new_cache = []
        for layer, layer_cache, ckv in zip(self.layers, cache, cross_kvs):
            x, updated = layer.step(x, layer_cache, pos, ckv)
            new_cache.append(updated)
        return self._logits(x)[:, 0, :], new_cache


class Whisper(nn.Module):
    """Encoder-decoder; `__call__` is the teacher-forcing pass (training),
    `encode`/`decode_*` power greedy inference."""

    cfg: WhisperConfig

    def setup(self):
        self.encoder = AudioEncoder(self.cfg, name="encoder")
        self.decoder = TextDecoder(self.cfg, name="decoder")

    def __call__(self, mel, tokens):
        return self.decoder(tokens, self.encoder(mel))

    def encode(self, mel):
        return self.encoder(mel)

    def decode_teacher(self, tokens, xa):
        return self.decoder(tokens, xa)

    def decode_init(self, batch, xa):
        return self.decoder.init_cache(batch), self.decoder.cross_kv(xa)

    def decode_step(self, token_t, pos, cache, cross_kvs):
        return self.decoder.step(token_t, pos, cache, cross_kvs)


# ---------------------------------------------------------------------------
# Greedy decoding (static-length scan)
# ---------------------------------------------------------------------------

def greedy_decode(model: Whisper, params, mel: jax.Array,
                  max_len: Optional[int] = None) -> jax.Array:
    """mel [B, F, M] -> token ids [B, max_len] (eot-padded).

    Jit-able end to end; the decode loop is a fixed-length `lax.scan` whose
    carry is (current token, cache, finished-flags)."""
    cfg = model.cfg
    max_len = max_len or cfg.n_text_ctx
    batch = mel.shape[0]

    xa = model.apply(params, mel, method=Whisper.encode)
    cache, cross_kvs = model.apply(params, batch, xa,
                                   method=Whisper.decode_init)

    prompt = jnp.array([cfg.sot_token, cfg.transcribe_token,
                        cfg.no_timestamps_token], jnp.int32)
    n_prompt = prompt.shape[0]

    def step(carry, pos):
        token, cache, finished = carry
        logits, cache = model.apply(params, token[:, None], pos, cache,
                                    cross_kvs, method=Whisper.decode_step)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # While still in the prompt, force the next prompt token.
        in_prompt = pos + 1 < n_prompt
        forced = jnp.where(in_prompt, prompt[jnp.minimum(pos + 1,
                                                         n_prompt - 1)],
                           nxt)
        nxt = jnp.where(finished, cfg.eot_token, forced)
        finished = finished | (nxt == cfg.eot_token)
        return (nxt, cache, finished), nxt

    token0 = jnp.full((batch,), cfg.sot_token, jnp.int32)
    finished0 = jnp.zeros((batch,), bool)
    (_, _, _), tokens = jax.lax.scan(
        step, (token0, cache, finished0), jnp.arange(max_len - 1))
    tokens = jnp.concatenate([token0[None], tokens], axis=0)  # [T, B]
    return tokens.T                                            # [B, T]


def audio_window_samples(cfg: WhisperConfig) -> int:
    """The fixed waveform window implied by the audio context: n_audio_ctx
    encoder positions x conv stride 2 x hop (30 s for the real configs)."""
    return cfg.n_audio_ctx * 2 * HOP_LENGTH


def transcribe_features(model: Whisper, params, audio: jax.Array,
                        max_len: Optional[int] = None) -> jax.Array:
    """waveform [B, T] -> token ids [B, L]: frontend + encode + greedy."""
    cfg = model.cfg
    audio = pad_or_trim(audio, audio_window_samples(cfg))
    mel = log_mel_spectrogram(audio, n_mels=cfg.n_mels)
    return greedy_decode(model, params, mel, max_len=max_len)
