"""LoRA fine-tuning for the encoder projections (serving-compatible).

`finetune_head` (train.py) adapts only the classifier head on frozen CLS
features — enough when the pretrained embedding space already separates
the classes.  When it doesn't, this module trains low-rank adapters on the
four projection GEMMs per layer (qkv, attn_out, mlp_up, mlp_down) jointly
with the head: ``W_eff = W + (alpha/rank) * A @ B`` with ``B`` zero-init,
so step 0 is exactly the pretrained model.

TPU-first by construction: the adapters are merged into the dense kernels
functionally INSIDE the jitted step (two small GEMMs per projection —
negligible next to the forward), so the training graph keeps the same
fused-QKV MXU layout as serving, and the returned tree is a plain float
param tree — orbax-checkpointable, engine-loadable (`checkpoint_dir`) and
int8-quantizable (`models/quant.py`) with zero serving-side changes.

The reference has no training surface at all; this extends the ⟨NEW⟩
train stage (SURVEY.md §7.6) the same way `models/train.py` does.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .encoder import Classifier, EncoderConfig
from .train import (
    TrainConfig,
    cross_entropy,
    epoch_batches,
    make_optimizer,
    prepare_finetune_arrays,
)

# Dense projection kernels that get adapters, as key paths into a layer
# dict.  Note the flax layout: the fused QKV is a flat "qkv/kernel" leaf
# on the attn module, while attn_out/mlp_up/mlp_down are nn.Dense
# submodules holding {"kernel", "bias"}.  (MoE expert kernels are
# deliberately excluded: adapting a per-expert 3-D kernel is
# rank-deficient per expert; adapt attention and train the router instead
# if MoE fine-tuning is ever needed.)
_TARGETS = (("attn", "qkv/kernel"), ("attn", "attn_out", "kernel"),
            ("mlp", "mlp_up", "kernel"), ("mlp", "mlp_down", "kernel"))
# Adapter dicts are keyed by the joined path; resolve back through this
# table ("qkv/kernel" itself contains a slash, so split() would be wrong).
_TARGET_BY_KEY = {"/".join(p): p for p in _TARGETS}


def _get_path(tree: Any, path: Tuple[str, ...]) -> Any:
    for key in path:
        if not isinstance(tree, dict) or key not in tree:
            return None
        tree = tree[key]
    return tree


def _copy_and_set(tree: Dict, path: Tuple[str, ...], value: Any) -> Dict:
    """Return a copy of ``tree`` with ``path`` replaced (containers along
    the path are shallow-copied; everything else is shared)."""
    out = dict(tree)
    if len(path) == 1:
        out[path[0]] = value
    else:
        out[path[0]] = _copy_and_set(out[path[0]], path[1:], value)
    return out


def init_lora_params(rng: jax.Array, params: Any, rank: int) -> Dict:
    """Adapters for every target kernel present in ``params``.

    Layout: ``{layers_i: {"attn/qkv/kernel": {"a": [in, r], "b": [r, ...out]},
    ...}}``.  ``a`` is scaled-normal, ``b`` zeros — the standard init that
    makes the adapted model exactly the base model before step 1.
    """
    enc = params["params"]["encoder"]
    lora: Dict[str, Dict[str, Dict[str, jax.Array]]] = {}
    for lname, layer in enc.items():
        if not lname.startswith("layers_"):
            continue
        adapters: Dict[str, Dict[str, jax.Array]] = {}
        for path in _TARGETS:
            kern = _get_path(layer, path)
            if kern is None:
                continue
            in_dim, out_shape = kern.shape[0], kern.shape[1:]
            rng, sub = jax.random.split(rng)
            adapters["/".join(path)] = {
                "a": (jax.random.normal(sub, (in_dim, rank), jnp.float32)
                      / np.sqrt(in_dim)),
                "b": jnp.zeros((rank,) + tuple(out_shape), jnp.float32),
            }
        if adapters:
            lora[lname] = adapters
    if not lora:
        raise ValueError("no LoRA target kernels found in params")
    return lora


def _delta(a: jax.Array, b: jax.Array) -> jax.Array:
    """A @ B for 2-D ([r, out]) or fused-QKV 3-D ([r, 3, h]) b."""
    return jnp.tensordot(a, b, axes=([1], [0]))


def lora_rank_of(lora: Dict) -> int:
    """The rank the adapters were initialized with (the ``a`` column dim)."""
    first_layer = next(iter(lora.values()))
    first = next(iter(first_layer.values()))
    return int(first["a"].shape[1])


def _merge_encoder(enc: Dict, lora: Dict, scale: float) -> Dict:
    """Fold adapters into a COPY of an encoder subtree — the one merge
    implementation, used by both the jitted training step and the
    checkpoint writer so they can never drift apart."""
    enc = dict(enc)
    for lname, adapters in lora.items():
        layer = enc[lname]
        for key, ab in adapters.items():
            path = _TARGET_BY_KEY[key]
            kern = _get_path(layer, path)
            layer = _copy_and_set(
                layer, path,
                kern.astype(jnp.float32) + scale * _delta(ab["a"], ab["b"]))
        enc[lname] = layer
    return enc


def merge_lora(params: Any, lora: Dict, rank: Optional[int] = None,
               alpha: float = 16.0) -> Any:
    """Fold the adapters into a NEW plain float param tree (base untouched).

    ``rank`` defaults to the adapters' own rank; passing a different value
    is rejected rather than silently mis-scaling every merged kernel.
    """
    actual = lora_rank_of(lora)
    if rank is not None and rank != actual:
        raise ValueError(f"rank {rank} does not match the adapters' "
                         f"rank {actual}")
    tree = jax.tree.map(lambda x: x, params)  # rebuild every container
    tree["params"]["encoder"] = _merge_encoder(
        tree["params"]["encoder"], lora, alpha / float(actual))
    return tree


def finetune_lora(ecfg: EncoderConfig, params: Any,
                  token_lists: Sequence[Sequence[int]],
                  labels: Sequence[int],
                  rank: int = 8, alpha: float = 16.0,
                  tc: TrainConfig = TrainConfig(learning_rate=1e-4,
                                                warmup_steps=10),
                  epochs: int = 10, batch_size: int = 16,
                  seed: int = 0,
                  max_len: Optional[int] = None
                  ) -> Tuple[Any, List[Dict[str, float]]]:
    """LoRA + head fine-tune; returns ``(merged_params, history)``.

    ``merged_params`` is a plain float tree — save it with
    `inference.checkpoint.save_params` and the engine's ``checkpoint_dir``
    path loads it like any full fine-tune.  Full forward/backward per step
    (unlike `finetune_head`'s frozen-feature shortcut), so use it when the
    head alone can't separate the classes.
    """
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    ids_np, mask_np, labels_np = prepare_finetune_arrays(
        ecfg, token_lists, labels, epochs, max_len)

    model = Classifier(ecfg)
    base_enc = params["params"]["encoder"]
    lora = init_lora_params(jax.random.PRNGKey(seed), params, rank)
    head = params["params"]["cls_head"]
    optimizer = make_optimizer(tc)
    opt_state = optimizer.init((lora, head))
    scale = alpha / float(rank)

    def apply_merged(base, lp, hp, ids, mask):
        return model.apply(
            {"params": {"encoder": _merge_encoder(base, lp, scale),
                        "cls_head": hp}}, ids, mask)

    @jax.jit
    def step(base, lp, hp, os_, ids, mask, y):
        def loss_fn(trainable):
            lp_, hp_ = trainable
            logits = apply_merged(base, lp_, hp_, ids, mask)
            loss = cross_entropy(logits, y, tc.label_smoothing)
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)((lp, hp))
        updates, os_ = optimizer.update(grads, os_, (lp, hp))
        (lp, hp) = optax.apply_updates((lp, hp), updates)
        return lp, hp, os_, loss, acc

    rng = np.random.default_rng(seed)
    history: List[Dict[str, float]] = []
    for _ in range(epochs):
        losses, accs = [], []
        for idx in epoch_batches(rng, len(token_lists), batch_size):
            lora, head, opt_state, loss, acc = step(
                base_enc, lora, head, opt_state,
                ids_np[idx], mask_np[idx], labels_np[idx])
            losses.append(float(loss))
            accs.append(float(acc))
        history.append({"loss": float(np.mean(losses)),
                        "accuracy": float(np.mean(accs))})

    merged = merge_lora(params, lora, rank, alpha)
    merged = {"params": {**merged["params"], "cls_head": head}}
    return merged, history
