"""Training step: classifier fine-tuning on the crawl stream.

The reference has no training at all — this is the ⟨NEW⟩ surface (SURVEY.md
§7.6) that makes the TPU build a framework rather than a port.  Everything is
a pure function over (params, opt_state, batch) jitted once over the mesh:
data parallelism over dp, tensor/expert over tp, sequence over sp, with XLA
inserting the gradient all-reduces (no hand-written psum — the sharded params
make XLA emit reduce-scatter/all-gather as needed).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from .encoder import Classifier, EncoderConfig


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 2e-5
    weight_decay: float = 0.01
    max_grad_norm: float = 1.0
    warmup_steps: int = 100
    label_smoothing: float = 0.0


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.linear_schedule(0.0, tc.learning_rate, tc.warmup_steps)
    return optax.chain(
        optax.clip_by_global_norm(tc.max_grad_norm),
        optax.adamw(schedule, weight_decay=tc.weight_decay),
    )


def cross_entropy(logits: jax.Array, labels: jax.Array,
                  smoothing: float = 0.0) -> jax.Array:
    n = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, n)
    if smoothing:
        onehot = onehot * (1.0 - smoothing) + smoothing / n
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def make_train_step(cfg: EncoderConfig, tc: TrainConfig = TrainConfig()
                    ) -> Tuple[Callable, Callable, optax.GradientTransformation]:
    """Returns (init_fn, step_fn, optimizer).

    init_fn(rng, ids, mask) -> (params, opt_state)
    step_fn(params, opt_state, ids, mask, labels) -> (params, opt_state, metrics)

    step_fn is pure and jit-ready; callers jit it with the mesh shardings
    from `parallel.sharding` (see __graft_entry__.dryrun_multichip).
    """
    model = Classifier(cfg)
    optimizer = make_optimizer(tc)

    def init_fn(rng, ids, mask):
        params = model.init(rng, ids, mask)["params"]
        return params, optimizer.init(params)

    def loss_fn(params, ids, mask, labels):
        logits = model.apply({"params": params}, ids, mask)
        loss = cross_entropy(logits, labels, tc.label_smoothing)
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, acc

    def step_fn(params, opt_state, ids, mask, labels):
        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, ids, mask, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss, "accuracy": acc}

    return init_fn, step_fn, optimizer
