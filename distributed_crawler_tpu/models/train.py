"""Training step: classifier fine-tuning on the crawl stream.

The reference has no training at all — this is the ⟨NEW⟩ surface (SURVEY.md
§7.6) that makes the TPU build a framework rather than a port.  Everything is
a pure function over (params, opt_state, batch) jitted once over the mesh:
data parallelism over dp, tensor/expert over tp, sequence over sp, with XLA
inserting the gradient all-reduces (no hand-written psum — the sharded params
make XLA emit reduce-scatter/all-gather as needed).

Two entry points:

- :func:`make_train_step` — full-model fine-tune (mesh-shardable).
- :func:`finetune_head` — head-only fine-tune on a FROZEN encoder: the
  closing move of the pretrained-load path, where `_load_pretrained`
  grafts a random head onto an E5-style encoder-only checkpoint
  (`inference/engine.py`).  The frozen encoder runs ONCE per example to
  cache CLS features; only the tiny pooler+head trains, so a labelled
  crawl slice fine-tunes in seconds even on CPU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .encoder import Classifier, ClassificationHead, Encoder, EncoderConfig


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 2e-5
    weight_decay: float = 0.01
    max_grad_norm: float = 1.0
    warmup_steps: int = 100
    label_smoothing: float = 0.0
    # Switch load-balancing aux-loss weight (the Switch-Transformer
    # default): keeps the router from collapsing onto one expert during
    # full fine-tuning of MoE configs.  No effect on dense models.
    moe_aux_weight: float = 0.01
    # Gradient accumulation: split each step's batch into this many
    # microbatches, run them through a lax.scan (ONE compiled program,
    # static shapes — the XLA-friendly loop), average the grads, apply
    # ONE optimizer update.  Trades step latency for effective batch
    # sizes that exceed a chip's activation memory; composes with remat
    # and with dp sharding (the microbatch slice keeps the dp layout).
    grad_accum_steps: int = 1


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.linear_schedule(0.0, tc.learning_rate, tc.warmup_steps)
    return optax.chain(
        optax.clip_by_global_norm(tc.max_grad_norm),
        optax.adamw(schedule, weight_decay=tc.weight_decay),
    )


def cross_entropy(logits: jax.Array, labels: jax.Array,
                  smoothing: float = 0.0) -> jax.Array:
    n = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, n)
    if smoothing:
        onehot = onehot * (1.0 - smoothing) + smoothing / n
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def make_train_step(cfg: EncoderConfig, tc: TrainConfig = TrainConfig()
                    ) -> Tuple[Callable, Callable, optax.GradientTransformation]:
    """Returns (init_fn, step_fn, optimizer).

    init_fn(rng, ids, mask) -> (params, opt_state)
    step_fn(params, opt_state, ids, mask, labels) -> (params, opt_state, metrics)

    step_fn is pure and jit-ready; callers jit it with the mesh shardings
    from `parallel.sharding` (see __graft_entry__.dryrun_multichip).
    """
    model = Classifier(cfg)
    optimizer = make_optimizer(tc)

    def init_fn(rng, ids, mask):
        params = model.init(rng, ids, mask)["params"]
        return params, optimizer.init(params)

    def loss_fn(params, ids, mask, labels):
        logits, mods = model.apply({"params": params}, ids, mask,
                                   mutable=["losses"])
        loss = cross_entropy(logits, labels, tc.label_smoothing)
        # Switch load-balancing aux (sowed per MoE layer, summed here);
        # zero for dense configs — the tree is empty.
        aux = jax.tree_util.tree_reduce(
            jnp.add, mods.get("losses", {}), jnp.float32(0))
        loss = loss + tc.moe_aux_weight * aux
        acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
        return loss, (acc, aux)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def step_fn(params, opt_state, ids, mask, labels):
        a = tc.grad_accum_steps
        if a <= 1:
            (loss, (acc, aux)), grads = grad_fn(params, ids, mask, labels)
        else:
            b = ids.shape[0]
            if b % a != 0:
                raise ValueError(
                    f"batch {b} not divisible by grad_accum_steps {a}")
            m = b // a
            micro = (ids.reshape(a, m, *ids.shape[1:]),
                     mask.reshape(a, m, *mask.shape[1:]),
                     labels.reshape(a, m, *labels.shape[1:]))

            def body(carry, xs):
                g_sum, l_sum, acc_sum, aux_sum = carry
                mids, mmask, mlabels = xs
                (mloss, (macc, maux)), g = grad_fn(params, mids, mmask,
                                                   mlabels)
                return (jax.tree.map(jnp.add, g_sum, g), l_sum + mloss,
                        acc_sum + macc, aux_sum + maux), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (g_sum, l_sum, acc_sum, aux_sum), _ = jax.lax.scan(
                body, (zeros, jnp.float32(0), jnp.float32(0),
                       jnp.float32(0)), micro)
            inv = 1.0 / a
            grads = jax.tree.map(lambda g: g * inv, g_sum)
            loss, acc, aux = l_sum * inv, acc_sum * inv, aux_sum * inv
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss, "accuracy": acc,
                                   "moe_aux": aux}

    return init_fn, step_fn, optimizer


# ---------------------------------------------------------------------------
# Head-only fine-tune on a frozen encoder (BASELINE config #3 closing loop)
# ---------------------------------------------------------------------------

def encode_cls_features(ecfg: EncoderConfig, params: Any,
                        token_lists: Sequence[Sequence[int]],
                        batch_size: int = 64,
                        buckets: Optional[Sequence[int]] = None
                        ) -> np.ndarray:
    """Run the FROZEN encoder over tokenized texts, returning the CLS
    hidden state [N, H] — the exact feature `EmbedderClassifier` feeds its
    `cls_head` (`encoder.py:236-247`), so a head trained on these features
    drops back into the fused inference model unchanged.

    Texts are grouped into length ``buckets`` (default: the engine's
    standard bucket ladder capped at ``ecfg.max_len``) so one long outlier
    doesn't force every batch to the dataset-wide max length.
    """
    from ..ops.padding import BucketSpec, bucket_for, pack_batch

    enc = Encoder(ecfg)
    enc_params = params["params"]["encoder"]
    if buckets is None:
        buckets = (32, 64, 128, 256, 512)
    lengths = tuple(b for b in sorted(buckets) if b <= ecfg.max_len) \
        or (ecfg.max_len,)
    spec = BucketSpec(lengths)

    @jax.jit
    def step(p, ids, mask):
        hidden = enc.apply({"params": p}, ids, mask)
        return hidden[:, 0, :].astype(jnp.float32)

    feats = np.zeros((len(token_lists), ecfg.hidden), np.float32)
    groups: Dict[int, List[int]] = {}
    for i, toks in enumerate(token_lists):
        groups.setdefault(bucket_for(len(toks), spec), []).append(i)
    for bucket, indices in sorted(groups.items()):
        for start in range(0, len(indices), batch_size):
            chunk = indices[start:start + batch_size]
            ids, mask = pack_batch(
                [list(token_lists[i]) for i in chunk],
                BucketSpec((bucket,)), batch_pad_to=batch_size)
            out = np.asarray(step(enc_params, ids, mask))
            feats[chunk] = out[:len(chunk)]
    return feats


def prepare_finetune_arrays(ecfg: EncoderConfig,
                            token_lists: Sequence[Sequence[int]],
                            labels: Sequence[int], epochs: int,
                            max_len: Optional[int] = None
                            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Shared fine-tune front door (full + LoRA loops): validate the
    dataset, then pack tokens into ONE static ``[N, L]`` shape for the
    whole run — L = longest sequence rounded up to a multiple of 32,
    capped at the encoder context.  Returns ``(ids, mask, labels)``."""
    if len(token_lists) != len(labels):
        raise ValueError(f"{len(token_lists)} texts vs {len(labels)} labels")
    if not token_lists:
        raise ValueError("empty training set")
    if epochs < 1:
        raise ValueError(f"epochs must be >= 1, got {epochs}")
    if min(labels) < 0:
        raise ValueError(f"negative label id {min(labels)} is not a class")
    n_labels = int(max(labels)) + 1
    if n_labels > ecfg.n_labels:
        raise ValueError(
            f"label id {n_labels - 1} exceeds head width {ecfg.n_labels}")

    seq = max(len(t) for t in token_lists)
    seq = min(ecfg.max_len, max_len or ecfg.max_len, ((seq + 31) // 32) * 32)
    ids_np = np.zeros((len(token_lists), seq), np.int32)
    mask_np = np.zeros((len(token_lists), seq), bool)
    for i, toks in enumerate(token_lists):
        toks = list(toks)[:seq]
        ids_np[i, :len(toks)] = toks
        mask_np[i, :len(toks)] = True
    return ids_np, mask_np, np.asarray(labels, np.int32)


def epoch_batches(rng: np.random.Generator, n: int, batch_size: int):
    """Shuffled minibatch index arrays for one epoch, every batch padded to
    the static ``batch_size`` (tail batches repeat earlier rows — the
    repeats only reweight the gradient slightly).  Shared by every
    fine-tune loop so the padding edge cases stay identical."""
    order = rng.permutation(n)
    for start in range(0, n, batch_size):
        idx = order[start:start + batch_size]
        if len(idx) < batch_size:
            idx = (np.concatenate([idx, order[:batch_size - len(idx)]])
                   if n >= batch_size else np.resize(idx, batch_size))
        yield idx


def finetune_head(ecfg: EncoderConfig, params: Any,
                  token_lists: Sequence[Sequence[int]],
                  labels: Sequence[int],
                  tc: TrainConfig = TrainConfig(learning_rate=1e-3,
                                                warmup_steps=10),
                  epochs: int = 20, batch_size: int = 32,
                  seed: int = 0,
                  buckets: Optional[Sequence[int]] = None
                  ) -> Tuple[Any, List[Dict[str, float]]]:
    """Fine-tune ONLY the classification head on a frozen encoder.

    Returns ``(new_params, history)`` where ``new_params`` is the full
    pytree with the trained ``cls_head`` swapped in (engine-ready) and
    ``history`` has one ``{"loss", "accuracy"}`` dict per epoch.
    """
    if len(token_lists) != len(labels):
        raise ValueError(f"{len(token_lists)} texts vs {len(labels)} labels")
    if not token_lists:
        raise ValueError("empty training set")
    if epochs < 1:
        raise ValueError(f"epochs must be >= 1, got {epochs}")
    if min(labels) < 0:
        # one_hot(-1) is an all-zero row: silent loss dilution, not a class.
        raise ValueError(f"negative label id {min(labels)} is not a class")
    n_labels = int(max(labels)) + 1
    if n_labels > ecfg.n_labels:
        raise ValueError(
            f"label id {n_labels - 1} exceeds head width {ecfg.n_labels}")

    feats = encode_cls_features(ecfg, params, token_lists,
                                batch_size=batch_size, buckets=buckets)
    labels_np = np.asarray(labels, np.int32)

    head = ClassificationHead(ecfg)
    head_params = params["params"]["cls_head"]
    optimizer = make_optimizer(tc)
    opt_state = optimizer.init(head_params)

    @jax.jit
    def step(hp, os_, x, y):
        def loss_fn(hp):
            logits = head.apply({"params": hp}, x)
            loss = cross_entropy(logits, y, tc.label_smoothing)
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(hp)
        updates, os_ = optimizer.update(grads, os_, hp)
        return optax.apply_updates(hp, updates), os_, loss, acc

    rng = np.random.default_rng(seed)
    history: List[Dict[str, float]] = []
    for _ in range(epochs):
        losses, accs = [], []
        for idx in epoch_batches(rng, len(feats), batch_size):
            head_params, opt_state, loss, acc = step(
                head_params, opt_state, feats[idx], labels_np[idx])
            losses.append(float(loss))
            accs.append(float(acc))
        history.append({"loss": float(np.mean(losses)),
                        "accuracy": float(np.mean(accs))})

    new_params = {"params": {**params["params"], "cls_head": head_params}}
    return new_params, history


def finetune_full(ecfg: EncoderConfig, params: Any,
                  token_lists: Sequence[Sequence[int]],
                  labels: Sequence[int],
                  tc: TrainConfig = TrainConfig(warmup_steps=10),
                  epochs: int = 10, batch_size: int = 16,
                  seed: int = 0,
                  max_len: Optional[int] = None,
                  state_dir: Optional[str] = None
                  ) -> Tuple[Any, List[Dict[str, float]]]:
    """FULL fine-tune: every encoder weight plus the head, through
    `make_train_step` (AdamW + warmup + clipping, Switch aux loss for MoE
    configs, optional lax.scan gradient accumulation via
    ``tc.grad_accum_steps``).  The heavyweight member of the fine-tune
    family — `finetune_head` trains on frozen features, `lora.finetune_lora`
    trains low-rank deltas; this one moves everything.

    ``state_dir`` makes the run RESUMABLE at epoch granularity: params +
    optimizer state + history checkpoint to ``{state_dir}/epoch_N`` after
    every epoch, and a restart picks up from the newest one.  Per-epoch
    rng seeding (``seed + epoch``) keeps the batch order identical to an
    uninterrupted run, so resume reproduces it exactly.

    Returns ``(new_params, history)`` where ``new_params`` is the full
    engine-ready pytree and ``history`` has one
    ``{"loss", "accuracy", "moe_aux"}`` dict per epoch.
    """
    ids_np, mask_np, labels_np = prepare_finetune_arrays(
        ecfg, token_lists, labels, epochs, max_len)

    _, step_fn, optimizer = make_train_step(ecfg, tc)
    train_params = params["params"]
    opt_state = optimizer.init(train_params)
    step = jax.jit(step_fn)

    start_epoch = 0
    history: List[Dict[str, float]] = []
    if state_dir:
        from ..inference.checkpoint import (
            latest_train_state,
            load_train_state,
        )

        prior = latest_train_state(state_dir)
        if prior is not None:
            done_epoch, train_params, opt_state, history = \
                load_train_state(prior, train_params, opt_state)
            start_epoch = done_epoch + 1
            if start_epoch > epochs:
                raise ValueError(
                    f"state_dir holds {start_epoch} completed epochs but "
                    f"only {epochs} were requested — raise epochs to "
                    f"continue or point state_dir elsewhere")

    for epoch in range(start_epoch, epochs):
        rng = np.random.default_rng(seed + epoch)
        losses, accs, auxes = [], [], []
        for idx in epoch_batches(rng, len(token_lists), batch_size):
            train_params, opt_state, metrics = step(
                train_params, opt_state,
                ids_np[idx], mask_np[idx], labels_np[idx])
            losses.append(float(metrics["loss"]))
            accs.append(float(metrics["accuracy"]))
            auxes.append(float(metrics["moe_aux"]))
        history.append({"loss": float(np.mean(losses)),
                        "accuracy": float(np.mean(accs)),
                        "moe_aux": float(np.mean(auxes))})
        if state_dir:
            from ..inference.checkpoint import save_train_state

            save_train_state(state_dir, epoch, train_params, opt_state,
                             history)

    return {"params": train_params}, history
