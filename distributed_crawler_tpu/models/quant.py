"""Float→int8 conversion of encoder checkpoints (serving-time, one-shot).

`quantize_encoder_params` rewrites a float `EmbedderClassifier`/`Encoder`
param tree into the layout `models/encoder.QuantDense` + the int8 fused-QKV
branch expect:

    layers_i/attn/qkv/kernel   [h,3,h] f32  →  qkv/kernel_q int8 + qkv/scale [3,h]
    layers_i/attn/attn_out/kernel          →  kernel_q + scale (+ bias kept f32)
    layers_i/{mlp/mlp_up, mlp/mlp_down}/kernel → likewise
    layers_i/moe/experts_{up,down}/kernel [e,in,out] → kernel_q + scale [e,out]

Everything else (embeddings, layernorms, the MoE router, pooler, head)
passes through
unchanged — those stay in the float path by design (`ops/quant.py`
module docstring).  The conversion is lossy and one-way: never write the
result back over a training checkpoint.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..ops.quant import quantize_weights

# Dense projections quantized per layer: flax module name → present under
# layers_i/<attn|mlp>/.  (MoE expert kernels are 3-D and handled
# separately below.)
_PROJ_MODULES = ("attn_out", "mlp_up", "mlp_down")


def _quantize_dense(mod: Dict[str, Any]) -> Dict[str, Any]:
    w_q, scale = quantize_weights(jnp.asarray(mod["kernel"], jnp.float32),
                                  contract_axis=0)
    out = {"kernel_q": w_q, "scale": scale}
    if "bias" in mod:
        out["bias"] = jnp.asarray(mod["bias"], jnp.float32)
    return out


def calibrate_activation_scales(model, params, ids, mask) -> Dict[str, Any]:
    """Run one float forward with calibration sows enabled and return the
    "calib" collection: per-projection input abs-max values, shaped like
    the module tree (``layers_i/attn/qkv`` → ``(absmax,)``).

    ``model`` must be built from a config with ``calibrate=True`` (and
    ``quant="none"``); feed a REPRESENTATIVE batch — the scales clip
    whatever exceeds them at serving time.
    """
    _out, state = model.apply(params, ids, mask, mutable=["calib"])
    return state["calib"]


def _calib_value(calib: Optional[Dict[str, Any]], layer: str, holder: str,
                 name: str) -> Any:
    """Fish one sown abs-max out of the calib tree; None when absent."""
    if calib is None:
        return None
    node = calib
    for key in ("encoder", layer, holder):
        if not isinstance(node, dict) or key not in node:
            # Bare-encoder trees have no "encoder" level.
            if key == "encoder":
                continue
            return None
        node = node[key]
    val = node.get(f"{name}_in") if isinstance(node, dict) else None
    if val is None:
        return None
    if isinstance(val, (tuple, list)):  # sow reduce keeps a 1-tuple
        val = val[0]
    return val


def _act_scale(absmax) -> jnp.ndarray:
    """Calibrated abs-max → static activation scale (x ≈ x_q * scale)."""
    return jnp.maximum(jnp.asarray(absmax, jnp.float32), 1e-8) / 127.0


def quantize_encoder_params(params: Any,
                            act_scales: Optional[Dict[str, Any]] = None
                            ) -> Any:
    """Return a new param tree with the projection GEMMs int8-quantized.

    Accepts the usual ``{"params": {...}}`` wrapper or a bare tree; the
    encoder may sit at top level or under ``encoder`` (Embedder/Classifier
    wrappers).  Idempotent on already-quantized trees.

    ``act_scales`` (a `calibrate_activation_scales` result) switches the
    layout to ``int8_static``: each projection additionally carries its
    calibrated scalar ``a_scale``.
    """
    from flax.core import unfreeze

    params = unfreeze(params)  # no-op on plain dicts
    wrapped = isinstance(params, dict) and set(params) == {"params"}
    tree = params["params"] if wrapped else params
    tree = dict(tree)
    enc_key = "encoder" if "encoder" in tree else None
    enc = dict(tree[enc_key]) if enc_key else tree
    calib = None
    if act_scales is not None:
        calib = unfreeze(act_scales)

    for name, layer in list(enc.items()):
        if not name.startswith("layers_"):
            continue
        layer = {k: dict(v) if isinstance(v, dict) else v
                 for k, v in layer.items()}
        attn = layer.get("attn")
        if isinstance(attn, dict) and "qkv/kernel" in attn:
            w_q, scale = quantize_weights(
                jnp.asarray(attn.pop("qkv/kernel"), jnp.float32),
                contract_axis=0)
            attn["qkv/kernel_q"] = w_q          # [h, 3, h] int8
            attn["qkv/scale"] = scale           # [3, h] f32
            attn["qkv/bias"] = jnp.asarray(attn["qkv/bias"], jnp.float32)
            absmax = _calib_value(calib, name, "attn", "qkv")
            if absmax is not None:
                attn["qkv/a_scale"] = _act_scale(absmax)
        for holder_name in ("attn", "mlp"):
            holder = layer.get(holder_name)
            if not isinstance(holder, dict):
                continue
            for mod_name in _PROJ_MODULES:
                mod = holder.get(mod_name)
                if isinstance(mod, dict) and "kernel" in mod:
                    holder[mod_name] = _quantize_dense(mod)
                    absmax = _calib_value(calib, name, holder_name,
                                          mod_name)
                    if absmax is not None:
                        holder[mod_name]["a_scale"] = _act_scale(absmax)
        moe = layer.get("moe")
        if isinstance(moe, dict):
            # Expert kernels [e, in, out] contract their MIDDLE axis, so
            # scales come out per (expert, output channel); the f32 router
            # passes through untouched (it is precision-critical and tiny).
            for kname in ("experts_up/kernel", "experts_down/kernel"):
                if kname in moe:
                    w_q, scale = quantize_weights(
                        jnp.asarray(moe.pop(kname), jnp.float32),
                        contract_axis=1)
                    moe[kname + "_q"] = w_q
                    moe[kname.replace("/kernel", "/scale")] = scale
        enc[name] = layer

    if enc_key:
        tree[enc_key] = enc
    else:
        tree = enc
    return {"params": tree} if wrapped else tree


def quantized_size_bytes(params: Any) -> int:
    """Total param bytes (diagnostic: int8 trees should be ~4× smaller on
    the projection kernels than their f32 source)."""
    import jax

    return sum(int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize
               for x in jax.tree.leaves(params) if hasattr(x, "shape"))
