"""TPU k-means over embeddings (BASELINE.md config #5: snowball crawl ->
E5-large embed -> clustering on a v5e-8).

TPU-first shape: the assignment step is one [N, D] x [D, K] matmul on the
MXU (||x - c||^2 = ||x||^2 - 2 x.c + ||c||^2, argmin over K drops the x^2
term); the update step is a one-hot einsum (segment-sum as matmul).  The
whole fit is a `lax.fori_loop` of those two ops — jit once, no host round
trips.

Data parallelism: `fit` is written against global arrays; under `jit` with
the embeddings sharded on a dp mesh axis XLA turns the per-cluster sums and
counts into `psum`s over ICI automatically.  k-means++-style seeding uses
distance-weighted sampling with a fixed number of rounds (static shapes).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class KMeansResult(NamedTuple):
    centroids: jax.Array      # [K, D] f32
    assignments: jax.Array    # [N] int32
    inertia: jax.Array        # scalar f32 — sum of squared distances


def _pairwise_neg_scores(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """-2 x.c + ||c||^2 for argmin distance (x^2 constant per row).
    x [N, D], centroids [K, D] -> [N, K] f32."""
    x = x.astype(jnp.float32)
    c = centroids.astype(jnp.float32)
    return -2.0 * (x @ c.T) + jnp.sum(c * c, axis=1)[None, :]


def assign(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """Nearest-centroid assignment [N] int32."""
    return jnp.argmin(_pairwise_neg_scores(x, centroids),
                      axis=1).astype(jnp.int32)


def update(x: jax.Array, assignments: jax.Array, k: int) -> Tuple[jax.Array,
                                                                  jax.Array]:
    """New centroids + counts via one-hot matmul (MXU-friendly segment sum)."""
    onehot = jax.nn.one_hot(assignments, k, dtype=jnp.float32)  # [N, K]
    sums = onehot.T @ x.astype(jnp.float32)                     # [K, D]
    counts = jnp.sum(onehot, axis=0)                            # [K]
    return sums, counts


def kmeans_plus_plus_init(x: jax.Array, k: int,
                          rng: jax.Array) -> jax.Array:
    """Distance-weighted seeding, one new center per round (static K rounds)."""
    n = x.shape[0]
    first = jax.random.randint(rng, (), 0, n)
    centroids = jnp.tile(x[first][None, :], (k, 1)).astype(jnp.float32)

    def body(i, carry):
        centroids, rng = carry
        rng, sub = jax.random.split(rng)
        d2 = jnp.min(
            jnp.maximum(_pairwise_neg_scores(x, centroids)
                        + jnp.sum(x.astype(jnp.float32) ** 2, axis=1,
                                  keepdims=True), 0.0), axis=1)
        probs = d2 / jnp.maximum(jnp.sum(d2), 1e-12)
        idx = jax.random.choice(sub, n, p=probs)
        centroids = centroids.at[i].set(x[idx].astype(jnp.float32))
        return centroids, rng

    centroids, _ = jax.lax.fori_loop(1, k, body, (centroids, rng))
    return centroids


@partial(jax.jit, static_argnames=("k", "iters", "init"))
def fit(x: jax.Array, k: int, iters: int = 25,
        rng: Optional[jax.Array] = None,
        init: str = "kmeans++") -> KMeansResult:
    """Lloyd's algorithm, fully on device.

    x [N, D] (any float dtype; accumulation in f32), returns KMeansResult.
    Empty clusters keep their previous centroid (counts clamped to >= 1 in
    the division only when empty)."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    if init == "kmeans++":
        centroids = kmeans_plus_plus_init(x, k, rng)
    else:
        idx = jax.random.choice(rng, x.shape[0], (k,), replace=False)
        centroids = x[idx].astype(jnp.float32)

    def body(_, centroids):
        assignments = assign(x, centroids)
        sums, counts = update(x, assignments, k)
        fresh = sums / jnp.maximum(counts, 1.0)[:, None]
        return jnp.where((counts > 0)[:, None], fresh, centroids)

    centroids = jax.lax.fori_loop(0, iters, body, centroids)
    assignments = assign(x, centroids)
    diff = x.astype(jnp.float32) - centroids[assignments]
    inertia = jnp.sum(diff * diff)
    return KMeansResult(centroids=centroids, assignments=assignments,
                        inertia=inertia)


def fit_sharded(x: jax.Array, k: int, mesh, iters: int = 25,
                rng: Optional[jax.Array] = None) -> KMeansResult:
    """Data-parallel fit: shard the embeddings over the mesh's dp axis and
    jit with replicated centroids — XLA inserts the cross-chip psums for the
    one-hot sums/counts (the scaling-book recipe: annotate, don't hand-write
    collectives)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import AXIS_DP

    x = jax.device_put(x, NamedSharding(mesh, P(AXIS_DP, None)))
    return fit(x, k, iters=iters, rng=rng)
