"""Model families for the TPU inference stage (BASELINE.md configs 2-5).

All models are Flax modules with bf16 compute and f32 params, named so the
sharding rules in `parallel.sharding` match their parameter paths:

- :mod:`encoder` — BERT/XLM-R-family text encoder: multilingual-E5
  (small/base/large) embedders and XLM-R classifiers, optional MoE MLP for
  expert parallelism.
- :mod:`train` — training/fine-tune step (optax) used by the multi-chip
  dry-run and classifier fine-tuning.
- :mod:`whisper` — Whisper-family ASR (tiny/base/small) for Telegram
  voice/video media (BASELINE config #4): log-mel frontend, audio encoder,
  KV-cached greedy decoder.
- :mod:`clustering` — TPU k-means over embeddings (BASELINE config #5).
"""

from .encoder import (
    Classifier,
    Embedder,
    EmbedderClassifier,
    EncoderConfig,
    E5_SMALL,
    E5_BASE,
    E5_LARGE,
    XLMR_BASE,
    TINY_TEST,
)
from .whisper import (
    WHISPER_BASE,
    WHISPER_SMALL,
    WHISPER_TEST,
    WHISPER_TINY,
    Whisper,
    WhisperConfig,
    greedy_decode,
    log_mel_spectrogram,
    transcribe_features,
)

__all__ = [
    "EncoderConfig",
    "EmbedderClassifier",
    "Embedder",
    "Classifier",
    "E5_SMALL",
    "E5_BASE",
    "E5_LARGE",
    "XLMR_BASE",
    "TINY_TEST",
    "WHISPER_BASE",
    "WHISPER_SMALL",
    "WHISPER_TEST",
    "WHISPER_TINY",
    "Whisper",
    "WhisperConfig",
    "greedy_decode",
    "log_mel_spectrogram",
    "transcribe_features",
]
