"""Model families for the TPU inference stage (BASELINE.md configs 2-5).

All models are Flax modules with bf16 compute and f32 params, named so the
sharding rules in `parallel.sharding` match their parameter paths:

- :mod:`encoder` — BERT/XLM-R-family text encoder: multilingual-E5
  (small/base/large) embedders and XLM-R classifiers, optional MoE MLP for
  expert parallelism.
- :mod:`train` — training/fine-tune step (optax) used by the multi-chip
  dry-run and classifier fine-tuning.

Whisper-small ASR for Telegram voice/video media (BASELINE config #4) is the
next family on the roadmap and will land as :mod:`whisper`.
"""

from .encoder import (
    Classifier,
    Embedder,
    EncoderConfig,
    E5_SMALL,
    E5_BASE,
    E5_LARGE,
    XLMR_BASE,
    TINY_TEST,
)

__all__ = [
    "EncoderConfig",
    "Embedder",
    "Classifier",
    "E5_SMALL",
    "E5_BASE",
    "E5_LARGE",
    "XLMR_BASE",
    "TINY_TEST",
]
