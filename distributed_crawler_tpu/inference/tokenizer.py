"""Host-side tokenization feeding the device queue.

The reference's analog is the 10-goroutine video→Post conversion pool
(`crawler/youtube/youtube_crawler.go:353-427`) — host preprocessing in front
of the sink.  Tokenization here is deliberately pluggable: the default
:class:`HashingTokenizer` is dependency-free and deterministic (stable FNV-1a
over word pieces), so the whole pipeline runs hermetically; a SentencePiece/HF
vocab drops in behind the same protocol when checkpoints with a real vocab
are loaded (`from_pretrained_dir`).
"""

from __future__ import annotations

import re
import unicodedata
from itertools import chain
from typing import List, Protocol, Sequence

PAD_ID = 0
CLS_ID = 1
SEP_ID = 2
UNK_ID = 3
_RESERVED = 4

_WORD_RE = re.compile(r"\w+|[^\w\s]", re.UNICODE)


class Tokenizer(Protocol):
    vocab_size: int

    def encode(self, text: str) -> List[int]: ...

    def encode_batch(self, texts: Sequence[str]) -> List[List[int]]: ...


def _fnv1a(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class HashingTokenizer:
    """Deterministic hashing tokenizer: NFKC-lowercase words + sub-word
    fallback for long tokens, mapped into [RESERVED, vocab) by FNV-1a.

    Not a linguistic vocab — a stable, collision-spread id assignment that
    exercises the exact device path (shapes, buckets, gather widths) the real
    sentencepiece vocab will, with zero model-asset dependencies.
    """

    # Whitespace-token memo: natural text is Zipfian, so a bounded cache
    # turns regex word-splitting AND the per-byte Python FNV loop into one
    # dict hit per token.  Keys are raw whitespace-separated tokens
    # (post-NFKC-lowercase), values are TUPLES of ids — the full regex
    # word/punctuation split plus fixed-width long-word pieces — so the
    # warm path is pure C end to end: str.split → map(dict.get) →
    # chain.from_iterable.  Ids are IDENTICAL to running _WORD_RE over
    # the whole text: neither \\w+ nor [^\\w\\s] can match across
    # whitespace, so per-token regex concatenation equals whole-text
    # regex.  Measured (63-word Zipf posts, warm): ~12k posts/sec for the
    # bare FNV loop -> ~45k with the word-level memo -> ~90k here.
    _CACHE_MAX = 1 << 20

    def __init__(self, vocab_size: int, max_word_len: int = 12):
        if vocab_size <= _RESERVED:
            raise ValueError(f"vocab_size must exceed {_RESERVED}")
        self.vocab_size = vocab_size
        self.max_word_len = max_word_len
        self._memo: dict = {}

    def _fnv_id(self, piece: str) -> int:
        return _RESERVED + _fnv1a(piece.encode("utf-8")) % \
            (self.vocab_size - _RESERVED)

    def _hash_token(self, token: str) -> tuple:
        """Slow path: regex-split one whitespace token into words and
        punctuation, hash each (long words — URLs, hashes — split into
        fixed-width pieces so near-identical long strings don't collide
        to one id), memoize the id tuple.

        Tokens much longer than max_word_len (unique deep-links, file
        hashes, base64 blobs) are hashed UNCACHED: they rarely repeat, and
        caching arbitrarily long keys would both balloon the memo's memory
        and evict the hot Zipfian words on each clear()."""
        w = self.max_word_len
        ids = []
        for piece in _WORD_RE.findall(token):
            if len(piece) <= w:
                ids.append(self._fnv_id(piece))
            else:
                ids.extend(self._fnv_id(piece[i:i + w])
                           for i in range(0, len(piece), w))
        out = tuple(ids)
        if len(token) <= 4 * w:
            memo = self._memo
            if len(memo) >= self._CACHE_MAX:
                memo.clear()  # crude but O(1) amortized; Zipf refills fast
            memo[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        text = unicodedata.normalize("NFKC", text or "").lower()
        toks = text.split()
        memo_get = self._memo.get
        vals = list(map(memo_get, toks))
        if None in vals:
            for i, v in enumerate(vals):
                if v is None:
                    # Re-probe first: an earlier miss in THIS text may have
                    # just memoized the same token.
                    hit = memo_get(toks[i])
                    vals[i] = hit if hit is not None \
                        else self._hash_token(toks[i])
        return [CLS_ID, *chain.from_iterable(vals), SEP_ID]

    def encode_batch(self, texts: Sequence[str]) -> List[List[int]]:
        return [self.encode(t) for t in texts]


def from_pretrained_dir(path: str):
    """Load a real tokenizer from a local directory (no network).

    Prefers a bare ``tokenizer.json`` via the `tokenizers` runtime (covers
    XLM-R/E5 fast tokenizers with no sentencepiece dependency); falls back
    to `transformers.AutoTokenizer`.  Callers fall back to
    :class:`HashingTokenizer` when both raise.
    """
    import os

    tj = os.path.join(path, "tokenizer.json")
    if os.path.exists(tj):
        from tokenizers import Tokenizer as RustTokenizer

        tok = RustTokenizer.from_file(tj)

        class _FastWrapper:
            vocab_size = int(tok.get_vocab_size())

            @staticmethod
            def encode(text: str) -> List[int]:
                return tok.encode(text).ids

            @staticmethod
            def encode_batch(texts: Sequence[str]) -> List[List[int]]:
                return [e.ids for e in tok.encode_batch(list(texts))]

            @staticmethod
            def decode(ids: Sequence[int]) -> str:
                return tok.decode(list(ids))

        return _FastWrapper()

    from transformers import AutoTokenizer  # local import by design

    tok = AutoTokenizer.from_pretrained(path, local_files_only=True)

    class _HFWrapper:
        vocab_size = int(tok.vocab_size)

        @staticmethod
        def encode(text: str) -> List[int]:
            return tok.encode(text, truncation=False)

        @staticmethod
        def encode_batch(texts: Sequence[str]) -> List[List[int]]:
            return [tok.encode(t, truncation=False) for t in texts]

        @staticmethod
        def decode(ids: Sequence[int]) -> str:
            return tok.decode(list(ids), skip_special_tokens=True)

    return _HFWrapper()
