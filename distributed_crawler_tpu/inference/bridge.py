"""The crawl -> TPU bridge: stored posts become record batches on the bus.

SURVEY.md §2.3(4) maps the reference's tandem crawler⇄validator pipeline to
crawl -> embed -> classify -> store; this is the coupling point.  The bridge
decorates any StateManager: every `store_post` still lands in the JSONL sink
(the crawl side is unchanged), and the post is also fed to a
`BatchAccumulator` whose completed batches are published to
`tpu-inference-batches`.  A deadline thread flushes partial batches so a
bursty crawl stream can't strand records below the batch size (the
"batching deadline vs p50 latency" tradeoff from SURVEY.md §7 hard part c).

Everything else delegates to the wrapped manager via __getattr__, so the
bridge composes with Local/Composite managers and the crawl engine is
unaware of it.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict

from ..bus.codec import BatchAccumulator, RecordBatch
from ..bus.messages import TOPIC_INFERENCE_BATCHES
from ..datamodel import Post
from ..utils import trace

logger = logging.getLogger("dct.inference.bridge")


class InferenceBridge:
    """StateManager decorator publishing record batches as posts arrive."""

    def __init__(self, sm, bus, crawl_id: str = "", batch_size: int = 256,
                 deadline_s: float = 0.05, topic: str = TOPIC_INFERENCE_BATCHES,
                 poll_interval_s: float = 0.02, dedupe_window: int = 65536,
                 tenant: str = ""):
        self._sm = sm
        self._bus = bus
        self._topic = topic
        # Tenant provenance (ISSUE 17): every batch this ingestion path
        # publishes carries the crawl's tenant label; empty falls back to
        # the documented default inside the accumulator.
        self._acc = BatchAccumulator(batch_size=batch_size,
                                     deadline_s=deadline_s,
                                     crawl_id=crawl_id,
                                     tenant=tenant)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.batches_published = 0
        self.posts_bridged = 0
        self.posts_deduped = 0
        # At-least-once crawling (worker reassignment, stale-work requeue,
        # orchestrator crash-resume) can legitimately re-crawl a page whose
        # posts already shipped; post_uid is deterministic (chat_id +
        # message_id), so a bounded recently-seen window keeps re-crawled
        # posts from double-counting downstream.  0 disables.
        self._dedupe_window = max(0, dedupe_window)
        self._seen_uids: "OrderedDict[str, None]" = OrderedDict()
        # Deadline flusher: a partial batch older than deadline_s ships even
        # if the crawl stalls.
        self._thread = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="dct-bridge-flush")
        self._poll_interval_s = poll_interval_s
        self._thread.start()

    # -- the decorated write path -----------------------------------------
    def store_post(self, channel_id: str, post: Post) -> None:
        self._sm.store_post(channel_id, post)
        now = time.monotonic()
        with self._lock:
            uid = post.post_uid
            if uid and self._dedupe_window:
                if uid in self._seen_uids:
                    self._seen_uids.move_to_end(uid)
                    self.posts_deduped += 1
                    return  # already shipped to inference once
                self._seen_uids[uid] = None
                while len(self._seen_uids) > self._dedupe_window:
                    self._seen_uids.popitem(last=False)
            self.posts_bridged += 1
            batch = self._acc.add(post, now)
        if batch is not None:
            self._publish(batch)

    # -- lifecycle ---------------------------------------------------------
    def flush(self) -> None:
        """Ship whatever is accumulated (end of crawl / shutdown)."""
        with self._lock:
            batch = self._acc.flush()
        if batch is not None:
            self._publish(batch)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self.flush()
        self._sm.close()

    def _poll_loop(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            with self._lock:
                batch = self._acc.poll(time.monotonic())
            if batch is not None:
                self._publish(batch)

    def _publish(self, batch: RecordBatch) -> None:
        try:
            # Root span of the batch's trace (the orchestrator-process
            # dispatch of inference work): queue wait, coalesce, and the
            # engine stages downstream all share batch.trace_id.
            with trace.span("orchestrator.dispatch",
                            trace_id=batch.trace_id, batch=batch.batch_id,
                            records=len(batch), crawl_id=batch.crawl_id,
                            tenant=batch.tenant):
                self._bus.publish(self._topic, batch.to_dict())
            self.batches_published += 1
        except Exception as e:
            logger.error("failed to publish record batch", extra={
                "batch_id": batch.batch_id, "records": len(batch),
                "error": str(e)})

    # -- everything else is the wrapped manager ----------------------------
    def __getattr__(self, name):
        return getattr(self._sm, name)
