"""TPU worker service: record batches in, embeddings+labels out.

The service half of SURVEY.md §7.6, shaped like the crawl worker
(`worker/worker.go:96-252`): subscribe to the inference topic, heartbeat on
the status topic every 30 s, process with busy/idle transitions — but the
unit of work is a RecordBatch and "processing" is a jitted device step.

Double buffering: the bus handler thread only decodes and enqueues; the feed
thread packs the next batch on host while the device runs the current one
(jax's async dispatch overlaps the two), so a bursty crawl stream keeps the
chip busy without the handler ever blocking on the device.
"""

from __future__ import annotations

import inspect
import json
import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..bus.codec import RecordBatch
from ..bus.messages import (
    MSG_HEARTBEAT,
    MSG_WORKER_STOPPING,
    TOPIC_INFERENCE_BATCHES,
    TOPIC_INFERENCE_RESULTS,
    TOPIC_SPANS,
    TOPIC_WORKER_STATUS,
    SpanBatchMessage,
    StatusMessage,
    WORKER_BUSY,
    WORKER_IDLE,
    WORKER_OFFLINE,
)
from ..utils import flight, profiling, trace
from ..utils.occupancy import QueueDepthSampler
from ..utils.metrics import (
    REGISTRY,
    MetricsRegistry,
    clear_costs_provider,
    clear_status_provider,
    serve_metrics,
    set_costs_provider,
    set_status_provider,
)
from ..utils.slo import SLOWatchdog, standard_slos
from ..utils.telemetry import TelemetryEmitter
from ..utils.timeseries import RegistrySampler
from .engine import InferenceEngine

logger = logging.getLogger(__name__)


def iter_results(provider, crawl_id: str,
                 storage_prefix: str = "inference"):
    """Yield result dicts across all per-batch files of a crawl, in
    batch-file order — the read side of the idempotent writeback."""
    base = f"{storage_prefix}/{crawl_id}/batches"
    for name in provider.list_dir(base):
        if not name.endswith(".jsonl"):
            continue
        text = provider.get_text(f"{base}/{name}")
        for line in (text or "").splitlines():
            if line:
                yield json.loads(line)


def build_serving_mesh(data: int = 0, seq: int = 1, tensor: int = 1,
                       devices: int = 0):
    """Construct the serving mesh from the ``parallel:`` config block
    (`--mesh-data` / `--mesh-seq` / `--mesh-tensor` / `--mesh-devices`),
    or return None for the historical single-device path.

    Semantics (docs/tpu.md "Multi-chip serving"):

    - everything at its default (``data=0, seq=1, tensor=1, devices=0``)
      → **None**: no mesh, the engine serves one device exactly as before;
    - ``devices=-1`` → span ALL visible devices: dp is whatever remains
      after seq/tensor (``parallel.mesh.best_mesh_config``);
    - ``devices=N`` (>0) → span the first N visible devices, dp from the
      remainder the same way;
    - ``data=N`` (>0) → explicit dp axis; the device count is then
      ``data*seq*tensor`` unless ``devices`` pins it (they must agree).

    Raises ValueError on invalid/conflicting flags or when the host has
    fewer devices than asked — serving on a silently smaller mesh than
    configured would invalidate every capacity assumption the flag
    encoded.  The count resolution itself is
    `parallel.mesh.serving_device_count` (shared with tools/loadtest.py
    so harness provisioning can't drift from mesh construction).
    """
    from ..parallel.mesh import (
        best_mesh_config,
        make_mesh,
        serving_device_count,
    )

    n = serving_device_count(data=data, seq=seq, tensor=tensor,
                             devices=devices)
    if n == 0:
        return None
    import jax

    avail = jax.devices()
    if n == -1:
        n = len(avail)
        # serving_device_count defers this conflict to the caller that
        # knows the visible count: devices=-1 plus an explicit dp that
        # doesn't match must raise, not silently override the operator's
        # axis (the same contract as an explicit --mesh-devices N).
        if int(data) > 0 and n != int(data) * max(1, int(seq)) \
                * max(1, int(tensor)):
            raise ValueError(
                f"mesh axes dp={data} sp={seq} tp={tensor} "
                f"({int(data) * max(1, int(seq)) * max(1, int(tensor))} "
                f"devices) conflict with --mesh-devices -1 "
                f"({n} visible devices)")
    if n > len(avail):
        raise ValueError(
            f"serving mesh wants {n} devices but only {len(avail)} are "
            f"visible (CPU recipe: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n} JAX_PLATFORMS=cpu)")
    cfg = best_mesh_config(n, tp=max(1, int(tensor)), sp=max(1, int(seq)))
    mesh = make_mesh(cfg, devices=list(avail[:n]))
    logger.info("serving mesh: %s over %d %s device(s)",
                dict(mesh.shape), n, avail[0].platform)
    return mesh


@dataclass
class TPUWorkerConfig:
    worker_id: str = "tpu-worker-0"
    heartbeat_s: float = 30.0
    queue_capacity: int = 64          # decoded batches awaiting the device
    metrics_port: int = 0             # 0 = don't serve; >0 = HTTP port
    profiler_port: int = 0            # 0 = off; >0 = jax.profiler gRPC port
    storage_prefix: str = "inference"
    write_embeddings: bool = True     # False: labels/scores only (smaller JSONL)
    # Bus-bandwidth knob, independent of write_embeddings: whether result
    # batches published on TOPIC_INFERENCE_RESULTS carry the full
    # embedding vectors.  Embeddings dominate the result frame size
    # (~3 KB/post at E5-large width), so a deployment with no downstream
    # consumer can turn this off — but the streaming clustering stage
    # (`cluster/`) REQUIRES it on, and config wiring rejects the
    # combination loudly at startup (`cli.py`) / scenario load
    # (`loadgen/gate.py`) instead of letting the cluster worker starve.
    publish_embeddings: bool = True
    # Device-stall watchdog.  Shared/tunneled TPUs have been observed to
    # wedge for minutes (a jitted call that normally takes ~100 ms never
    # returns); the bus's ack-timeout requeues the frame, but the worker
    # thread itself stays stuck.  After ``stall_warn_s`` mid-step the
    # watchdog logs + counts the stall and flags /status; after
    # ``stall_exit_s`` (0 = never) it hard-exits the process so a
    # supervisor restarts it — safe by design: un-acked frames requeue and
    # the per-batch writeback is idempotent.  Warmup compiles run under
    # the same watchdog (TPUWorker.warmup), so size stall_warn_s above
    # the first-compile time of the largest bucket, or configure
    # `enable_compilation_cache` to make restart warmups near-instant.
    stall_warn_s: float = 120.0       # 0 disables the watchdog
    stall_exit_s: float = 0.0         # 0 = warn only, never exit
    # Coalescing feed: one dequeue drains up to this many queued batches and
    # runs them through the engine as ONE token stream (packed when ``pack``
    # is on), then fans results back so every RecordBatch still gets its own
    # ack and idempotent writeback.  1 = process one batch per dispatch (the
    # pre-coalescing behavior).
    coalesce_batches: int = 4
    # Sequence packing (`engine.run_tokenized(..., pack=True)`): short
    # sequences share bucket rows behind segment masks.  Turn off for
    # long-sequence-dominated streams, where rows pack 1:1 anyway.
    pack: bool = True
    # SLO budgets (`utils/slo.py`), evaluated once per heartbeat over the
    # spans completed since the previous beat; 0 = no budget declared.
    # Breaches count in slo_breach_total{slo}, WARN-log the offending
    # trace_id, and land in the flight-recorder ring.
    slo_batch_p95_ms: float = 0.0     # p95 of tpu_worker.process/coalesce
    slo_queue_wait_ms: float = 0.0    # p95 of tpu_worker.queue_wait
    # Whole-pipeline batch age (RecordBatch.created_at -> device), the
    # budget that catches frames stranded on the broker while this worker
    # was down/restarting — queue_wait can't see that leg.
    slo_batch_age_ms: float = 0.0     # p95 of tpu_worker.batch_age
    # Auto profiler capture: a device batch slower than this many ms
    # triggers one bounded jax.profiler capture to --dump-dir (one at a
    # time; `utils/profiling.py`).  0 = off.
    profile_on_slow_ms: float = 0.0
    # Span export (`utils/trace.py:SpanExporter` -> SpanBatchMessage on
    # TOPIC_SPANS): completed spans periodically ship to the
    # orchestrator's TraceCollector so /dtraces can assemble one
    # distributed trace per work item.  0 = never ship (local /traces
    # still works).  The per-batch bound and the whole-trace sample rate
    # keep a hot worker's export traffic flat.
    span_export_interval_s: float = 15.0
    span_export_max_spans: int = 512
    span_sample_rate: float = 1.0


class TPUWorker:
    """Consume RecordBatches from the bus, run the engine, write results.

    ``provider`` is any `state.providers.StorageProvider`; results land as
    one JSONL file per batch under
    `{storage_prefix}/{crawl_id}/batches/{batch_id}.jsonl` — the same sink
    family the crawler writes posts to, per the north star.  Use
    :func:`iter_results` to read them back as one stream.
    """

    def __init__(self, bus, engine: InferenceEngine,
                 provider=None,
                 cfg: TPUWorkerConfig = TPUWorkerConfig(),
                 registry: MetricsRegistry = REGISTRY):
        self.bus = bus
        self.engine = engine
        self.provider = provider
        self.cfg = cfg
        # Entries are (batch, ack, enqueue_monotonic): the third field is
        # what turns queue wait from a guess into a span.
        self._queue: "queue.Queue[Tuple[RecordBatch, Any, float]]" = \
            queue.Queue(cfg.queue_capacity)
        self._stop = threading.Event()
        self._threads: list = []
        self._idle = threading.Condition()
        self._inflight = 0          # batches accepted but not yet finished
        self._profiler_started = False
        self._started_at = 0.0
        self._processed = 0
        self._errors = 0
        self._metrics_server = None
        self._killed = False
        self._stop_announced = False
        self._step_started: Optional[float] = None   # monotonic, while in-step
        self._stall_warned = False
        self._watchdog_started = False
        self._exit_fn = None          # test seam; None -> os._exit
        self.m_queue_depth = registry.gauge(
            "tpu_worker_queue_depth",
            "decoded batches awaiting device (time-weighted rolling mean "
            "— an edge-triggered gauge aliases between scrapes)")
        # Time-weighted sampler over the gauge: enqueue/dequeue edges
        # feed it, the heartbeat re-samples it, so scrapes read what the
        # depth WAS over the window, not the last edge's leftovers.
        self._depth = QueueDepthSampler(self.m_queue_depth)
        self.m_stalls = registry.counter(
            "tpu_worker_device_stalls_total",
            "device steps exceeding stall_warn_s")
        self.m_batches = registry.counter(
            "tpu_worker_batches_total", "record batches processed")
        self.m_batch_age = registry.histogram(
            "tpu_worker_batch_age_seconds",
            "bus transit + queue wait per batch")
        self.m_coalesce = registry.histogram(
            "tpu_worker_coalesced_group_batches",
            "record batches coalesced into one device stream")
        # Outcome-labeled twin of m_batches: the ok/error split that the
        # single total hides (use .labels(outcome=...)).
        self.m_outcomes = registry.counter(
            "tpu_worker_batch_outcomes_total",
            "record batches by final commit outcome")
        # Telemetry-rich heartbeats: device memory, compile-cache deltas,
        # batch outcomes, per-stage latency digest — the fleet-view feed.
        self._telemetry = TelemetryEmitter(
            engine=engine, include_device=True,
            counters={"batch_outcomes": self.m_outcomes})
        # SLO watchdog: evaluated once per heartbeat over the spans since
        # the last beat.  Constructed even with no budgets declared (an
        # empty budget list evaluates to nothing) so /costs always shows
        # the slo map.
        self._slo = SLOWatchdog(
            standard_slos(batch_p95_ms=cfg.slo_batch_p95_ms,
                          queue_wait_ms=cfg.slo_queue_wait_ms,
                          batch_age_ms=cfg.slo_batch_age_ms),
            registry=registry)
        # Watchtower self-sampling (utils/timeseries.py): every metric
        # in THIS worker's registry becomes a rolling series once per
        # heartbeat, so the worker's own /timeseries carries history
        # that survives orchestrator restarts.
        self._ts_sampler = RegistrySampler(registry)
        # Span export cursor: starts at NOW so a fresh worker never
        # re-ships whatever history the process-wide ring carries; the
        # name filter ships only THIS worker's stages (shared-process
        # deployments must not re-export their neighbors' spans).
        self._span_exporter = trace.SpanExporter(
            max_spans=cfg.span_export_max_spans,
            sample_rate=cfg.span_sample_rate,
            name_prefixes=("tpu_worker.", "engine."))
        self._last_span_export = time.monotonic()
        # Capability probes, not flags: test doubles and older engines that
        # predate pack/coalescing keep working through the one-batch path.
        self._engine_coalesces = (
            callable(getattr(getattr(engine, "tokenizer", None),
                             "encode_batch", None))
            and callable(getattr(engine, "run_tokenized", None))
            and self._accepts_pack(getattr(engine, "run_tokenized", None)))
        self._engine_run_packs = self._accepts_pack(
            getattr(engine, "run", None))

    @staticmethod
    def _accepts_pack(fn) -> bool:
        try:
            return fn is not None and \
                "pack" in inspect.signature(fn).parameters
        except (TypeError, ValueError):
            return False

    def get_status(self) -> dict:
        """Status map for the /status endpoint (the `GetStatus()` analog
        the crawl orchestrator/worker expose, `worker.go:459`)."""
        started = self._step_started
        step_age = (time.monotonic() - started) if started is not None else 0.0
        threshold = self._stall_threshold()
        mesh = getattr(self.engine, "mesh", None)
        return {
            "worker_id": self.cfg.worker_id,
            "model": self.engine.cfg.model,
            "n_devices": getattr(self.engine, "n_devices", 1),
            "mesh": {str(k): int(v) for k, v in mesh.shape.items()}
            if mesh is not None else None,
            "is_running": not self._stop.is_set() and bool(self._threads),
            "queue_depth": self._queue.qsize(),
            "inflight": self._inflight,
            "processed_batches": self._processed,
            "error_batches": self._errors,
            "device_step_age_s": round(step_age, 1),
            "device_stalled": bool(threshold and step_age >= threshold),
            "uptime_s": (time.monotonic() - self._started_at)
            if self._started_at else 0.0,
        }

    def get_costs(self) -> dict:
        """The /costs body: the engine's cost/efficiency snapshot plus the
        worker's SLO state, per-tenant spend rows, and profiler status."""
        snap_fn = getattr(self.engine, "cost_snapshot", None)
        out = dict(snap_fn()) if callable(snap_fn) else {}
        out["worker_id"] = self.cfg.worker_id
        out["slo"] = self._slo.snapshot()
        ledger = self._tenant_ledger()
        if ledger is not None:
            out["tenants"] = ledger.snapshot()
        out["profiler"] = profiling.PROFILER.snapshot()
        return out

    # -- tenant attribution (ISSUE 17) -------------------------------------
    def _tenant_ledger(self):
        """The engine meter's TenantLedger, when the engine has one
        (test doubles and older engines simply don't attribute)."""
        return getattr(getattr(self.engine, "meter", None), "tenants", None)

    def _set_meter_tenants(self, weights: Dict[str, float]) -> None:
        """Declare the tenant split for the NEXT engine dispatches."""
        set_fn = getattr(getattr(self.engine, "meter", None),
                         "set_tenants", None)
        if callable(set_fn):
            set_fn(weights)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._started_at = time.monotonic()
        set_status_provider(self.get_status)
        set_costs_provider(self.get_costs)
        self.bus.subscribe(TOPIC_INFERENCE_BATCHES, self._handle_payload)
        self._start_watchdog()
        for target, name in ((self._feed_loop, "tpu-feed"),
                             (self._heartbeat_loop, "tpu-heartbeat")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        if self.cfg.metrics_port:
            self._metrics_server = serve_metrics(self.cfg.metrics_port)
        if self.cfg.profiler_port:
            # The pprof-endpoint analog (`main.go:60-80` served :6060
            # unconditionally): a jax.profiler gRPC server that
            # TensorBoard / `jax.profiler.trace` clients attach to for
            # on-demand device traces.  Guarded (`utils/profiling.py`):
            # an unavailable or already-started profiler logs a WARNING
            # instead of killing worker startup, and the same module's
            # /profile capture shares jax's one profiler session.
            self._profiler_started = profiling.start_profiler_server(
                self.cfg.profiler_port)
        logger.info("tpu worker started", extra={
            "worker_id": self.cfg.worker_id,
            "model": self.engine.cfg.model})

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        # Unregister OUR providers (only if still active — another
        # component may have registered since) so a later server in this
        # process 404s instead of serving a dead worker's maps.
        clear_status_provider(self.get_status)
        clear_costs_provider(self.get_costs)
        for t in self._threads:
            t.join(timeout=timeout_s)
        if self.cfg.span_export_interval_s > 0:
            # Graceful stop ships the span tail (kill() deliberately
            # doesn't — a crashed process exports nothing).
            self.export_spans()
        # Announce the clean shutdown so the fleet view marks this worker
        # OFFLINE instead of letting it age into "stale" (an autoscaler
        # retiring a worker must not trip the stale_worker alert minutes
        # later).  Graceful stops only — kill() stays silent, the way a
        # SIGKILLed process sends nothing.
        self._announce_stopping()
        if self.provider is not None:
            flush = getattr(self.provider, "flush", None)
            if callable(flush):
                flush()  # push any provider-side write buffering
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
        if self._profiler_started:
            profiling.stop_profiler_server()
            self._profiler_started = False

    def kill(self) -> None:
        """Abrupt-death simulation (the chaos/`loadgen` seam): halt the
        feed/heartbeat/watchdog threads WITHOUT draining, flushing the
        provider, sending a stopping status, or acking queued batches —
        the in-process analog of SIGKILL.  Un-acked frames requeue
        server-side on manual-ack buses (the caller closes this worker's
        RemoteBus to tear the pull stream down); the /status and /costs
        providers are left registered, exactly as a dead process leaves
        its endpoints unreachable rather than deregistered."""
        self._killed = True
        self._stop.set()
        flight.record("worker_kill", worker=self.cfg.worker_id,
                      queue_depth=self._queue.qsize(),
                      inflight=self._inflight)
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()

    def _announce_stopping(self) -> None:
        """Best-effort worker_stopping status on graceful stop (the
        CrawlWorker discipline): the fleet view maps it to OFFLINE, so a
        retired worker is "cleanly gone", never "stale".  Idempotent —
        gate teardown may stop a handle twice — and silent after kill()
        (SIGKILL fidelity)."""
        if self._killed or self._stop_announced:
            return
        self._stop_announced = True
        try:
            self.bus.publish(TOPIC_WORKER_STATUS, StatusMessage.new(
                self.cfg.worker_id, MSG_WORKER_STOPPING, WORKER_OFFLINE,
                tasks_processed=self._processed,
                tasks_success=self._processed - self._errors,
                tasks_error=self._errors,
                uptime_s=time.monotonic() - self._started_at,
                worker_type="tpu").to_dict())
        except Exception as e:  # a dead bus must not break shutdown
            logger.debug("stopping announcement failed: %s", e)

    def evaluate_slos(self) -> list:
        """One SLO evaluation tick on demand (the heartbeat loop's twin):
        digests spans completed since the previous tick against the
        declared budgets and returns the breach records.  The loadgen
        gate calls this at phase boundaries so breach attribution is
        deterministic instead of riding heartbeat timing."""
        return self._slo.evaluate()

    def export_spans(self) -> int:
        """Ship spans completed since the last export as one
        SpanBatchMessage on TOPIC_SPANS; returns the count shipped.
        The heartbeat loop calls this on ``span_export_interval_s``; the
        loadgen gate calls it at phase boundaries so trace assembly is
        deterministic.  Never raises — span telemetry must not take a
        serving worker down with it."""
        try:
            spans, dropped = self._span_exporter.collect()
            if not spans and not dropped:
                return 0
            msg = SpanBatchMessage.new(
                self.cfg.worker_id, [s.to_dict() for s in spans],
                dropped=dropped)
            self.bus.publish(TOPIC_SPANS, msg.to_dict())
            return len(spans)
        except Exception as e:
            logger.warning("span export failed: %s", e)
            return 0

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every accepted batch — queued OR mid-process — has
        finished, so `drain(); stop()` never cuts off the final
        writeback/ack.  ``_inflight`` counts from enqueue to completion."""
        with self._idle:
            return self._idle.wait_for(
                lambda: self._inflight == 0, timeout=timeout_s)

    # -- bus handler (never blocks on the device) --------------------------
    def _handle_payload(self, payload: Dict[str, Any], ack=None) -> None:
        """``ack`` is supplied by manual-ack buses (RemoteBus): the frame is
        acked only after the batch is processed AND written back, so a
        worker crash mid-queue requeues it server-side instead of losing
        it.  Buses without acks (InMemoryBus) call with one argument."""
        batch = RecordBatch.from_dict(payload)
        if not batch.records:
            if ack is not None:
                ack(True)
            return
        # Raising into the bus (queue full) triggers redelivery — the bus's
        # retry semantics are the backpressure path, as in the reference's
        # handler-error-means-retry contract (`pubsub.go:157-171`).
        # The in-flight count covers enqueue→completion, so drain() sees the
        # batch the moment it is accepted (no queue-vs-processing gap).
        with self._idle:
            self._inflight += 1
        try:
            self._queue.put((batch, ack, time.monotonic()), timeout=5.0)
        except queue.Full:
            self._finish_one()
            if ack is not None:
                self.m_outcomes.labels(outcome="requeued").inc()
                flight.record("batch", batch=batch.batch_id,
                              outcome="requeued", reason="queue_full")
                ack(False)  # requeue server-side; don't block the stream
                return
            raise
        self._depth.update(self._queue.qsize())

    def _finish_one(self) -> None:
        with self._idle:
            self._inflight -= 1
            if self._inflight == 0:
                self._idle.notify_all()

    # -- feed loop (coalescing) --------------------------------------------
    def _feed_loop(self) -> None:
        """Drain up to ``coalesce_batches`` queued batches per device
        dispatch and run them as one (packed) stream — a bursty crawl
        stream fills bucket rows across RecordBatch boundaries instead of
        padding each partial batch up to batch_size on its own."""
        timeline = getattr(self.engine, "timeline", None)
        while not self._stop.is_set():
            try:
                items = [self._queue.get(timeout=0.1)]
            except queue.Empty:
                # The queue ran dry: the device is idle because there is
                # NO work — the next dispatch opens a new stream, so the
                # wait here never scores as a pipeline bubble
                # (`utils/occupancy.py`).
                if timeline is not None:
                    timeline.start_stream()
                continue
            while len(items) < max(1, self.cfg.coalesce_batches):
                try:
                    items.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            self._depth.update(self._queue.qsize())
            try:
                self._process_group(items)
            finally:
                for _ in items:
                    self._finish_one()

    def _process_group(self,
                       items: List[Tuple[RecordBatch, Any, float]]) -> None:
        now = time.monotonic()
        ledger = self._tenant_ledger()
        for batch, _, enq_t in items:
            # Queue wait as a span of each batch's own trace: the time
            # between the bus handler's enqueue and this dequeue, i.e.
            # what the batch spent behind its neighbors.
            trace.record("tpu_worker.queue_wait", now - enq_t,
                         trace_id=batch.trace_id, batch=batch.batch_id,
                         worker=self.cfg.worker_id, tenant=batch.tenant)
            if ledger is not None and batch.tenant:
                ledger.observe_queue_wait(batch.tenant, now - enq_t)
        if len(items) == 1 or not self._engine_coalesces:
            for batch, ack, _ in items:
                self._process_one(batch, ack)
            return
        self.m_coalesce.observe(len(items))
        # Tokenize per batch FIRST: a record whose text cannot tokenize
        # fails its own batch here, before any neighbor joins it on device.
        good: List[Tuple[RecordBatch, Any, List[List[int]]]] = []
        for batch, ack, _ in items:
            try:
                with trace.span("engine.tokenize",
                                trace_id=batch.trace_id,
                                records=len(batch.records)):
                    toks = self.engine.tokenizer.encode_batch(batch.texts())
                self._observe_age(batch)
                good.append((batch, ack, toks))
            except Exception as e:
                self._errors += 1
                self.m_outcomes.labels(outcome="error").inc()
                logger.exception("batch %s failed to tokenize: %s",
                                 batch.batch_id, e)
                if ack is not None:
                    ack(False)
        if not good:
            return
        all_toks = [t for _, _, toks in good for t in toks]
        # Per-tenant weight of this device stream = real token counts, so
        # the meter's ledger charges the coalesced dispatch fairly.
        weights: Dict[str, float] = {}
        for batch, _, toks in good:
            weights[batch.tenant] = weights.get(batch.tenant, 0.0) \
                + max(1, sum(len(t) for t in toks))
        self._set_meter_tenants(weights)
        dominant = max(weights, key=weights.get) if weights else ""
        started = self._step_started = time.monotonic()
        try:
            # The coalesce span runs under the FIRST batch's trace (one
            # device stream has one ambient context); the engine's stage
            # spans nest below it, and the co-batched ids are attrs so the
            # other batches' traces point here.
            with trace.span("tpu_worker.coalesce",
                            trace_id=good[0][0].trace_id,
                            batches=len(good),
                            batch_ids=[b.batch_id for b, _, _ in good],
                            sequences=len(all_toks),
                            tenant=dominant):
                results = self.engine.run_tokenized(all_toks,
                                                    pack=self.cfg.pack)
        except Exception as e:
            # The combined step failed; fall back to per-batch execution so
            # one poisoned batch cannot take its coalesced neighbors down.
            logger.exception(
                "coalesced step over %d batches failed (%s); isolating "
                "per batch", len(good), e)
            results = None
        finally:
            self._step_started = None
            self._stall_warned = False
            self._after_step(time.monotonic() - started,
                             good[0][0].trace_id)
        if results is None:
            for batch, ack, toks in good:
                self._process_tokenized(batch, ack, toks)
            return
        # Fan results back to each originating batch: every batch keeps its
        # OWN publish + idempotent writeback + ack, and a failure in one
        # batch's commit nacks only that batch.
        off = 0
        for batch, ack, toks in good:
            rs = results[off:off + len(toks)]
            off += len(toks)
            self._finish_batch(batch, ack, lambda rs=rs: rs)

    def _finish_batch(self, batch: RecordBatch, ack, produce) -> None:
        """The ONE copy of the commit/ack/error accounting every path
        shares; ``produce`` yields the batch's results (or raises)."""
        try:
            results = produce()
            with trace.span("tpu_worker.commit", trace_id=batch.trace_id,
                            batch=batch.batch_id,
                            records=len(batch.records)):
                self._commit(batch, results)
            self._processed += 1
            self.m_outcomes.labels(outcome="ok").inc()
            flight.record("batch", batch=batch.batch_id, outcome="ok",
                          records=len(batch.records))
            self._ack(batch, ack, True)
        except Exception as e:
            self._errors += 1
            self.m_outcomes.labels(outcome="error").inc()
            flight.record("batch", batch=batch.batch_id, outcome="error",
                          error=str(e))
            logger.exception("batch %s failed: %s", batch.batch_id, e)
            self._ack(batch, ack, False)

    def _ack(self, batch: RecordBatch, ack, ok: bool) -> None:
        if ack is None:
            return
        t0 = time.perf_counter()
        ack(ok)
        # Retroactive span: on RemoteBus this is the Ack RPC round trip
        # closing the at-least-once loop, and it is the LAST hop of the
        # batch's trace.
        trace.record("tpu_worker.ack", time.perf_counter() - t0,
                     trace_id=batch.trace_id, batch=batch.batch_id, ok=ok)

    def _run_step(self, fn, trace_id: str = ""):
        """Run a device step under the stall-watchdog bookkeeping."""
        started = self._step_started = time.monotonic()
        try:
            return fn()
        finally:
            self._step_started = None
            self._stall_warned = False
            self._after_step(time.monotonic() - started, trace_id)

    def _after_step(self, elapsed_s: float, trace_id: str) -> None:
        """Slow-batch hook (``--profile-on-slow-ms``): a device step past
        the threshold fires ONE bounded auto profiler capture to
        --dump-dir (skipped while a capture runs) and a flight event, so
        the trace that explains the slowness exists before anyone asks.

        Never raises: this runs in the serving path's ``finally`` — an
        observability failure (e.g. thread exhaustion in capture_async)
        must not nack an already-computed batch, nor mask the engine's
        own exception in the coalesce path."""
        try:
            self._slow_batch_hook(elapsed_s, trace_id)
        except Exception as e:
            logger.warning("slow-batch hook failed: %s", e)

    def _slow_batch_hook(self, elapsed_s: float, trace_id: str) -> None:
        threshold = self.cfg.profile_on_slow_ms
        elapsed_ms = elapsed_s * 1000.0
        if threshold <= 0 or elapsed_ms < threshold:
            return
        fired = profiling.capture_async(
            reason=f"slow batch {elapsed_ms:.0f}ms")
        flight.record("slow_batch", worker=self.cfg.worker_id,
                      elapsed_ms=round(elapsed_ms, 1),
                      threshold_ms=threshold, trace_id=trace_id,
                      profile_capture=fired)
        logger.warning(
            "device batch took %.0fms >= profile_on_slow_ms %.0fms "
            "(trace=%s); auto profiler capture %s",
            elapsed_ms, threshold, trace_id,
            "started" if fired else "skipped (one already running)",
            extra={"worker_id": self.cfg.worker_id})

    def _process_one(self, batch: RecordBatch, ack) -> None:
        def produce():
            self._observe_age(batch)
            self._set_meter_tenants(
                {batch.tenant: max(1, len(batch.records))})
            # Rooted at the batch's own trace: engine.run's tokenize and
            # stage spans nest under this.
            with trace.span("tpu_worker.process", trace_id=batch.trace_id,
                            batch=batch.batch_id,
                            records=len(batch.records),
                            tenant=batch.tenant):
                if self.cfg.pack and self._engine_run_packs:
                    return self._run_step(
                        lambda: self.engine.run(batch.texts(), pack=True),
                        trace_id=batch.trace_id)
                return self._run_step(
                    lambda: self.engine.run(batch.texts()),
                    trace_id=batch.trace_id)

        self._finish_batch(batch, ack, produce)

    def _process_tokenized(self, batch: RecordBatch, ack, toks) -> None:
        """Per-batch fallback after a failed coalesced step: the batch was
        already tokenized and age-observed when the group formed, so reuse
        the token lists instead of re-running the text front door."""
        def produce():
            self._set_meter_tenants(
                {batch.tenant: max(1, sum(len(t) for t in toks))})
            with trace.span("tpu_worker.process", trace_id=batch.trace_id,
                            batch=batch.batch_id, isolated=True,
                            tenant=batch.tenant):
                return self._run_step(
                    lambda: self.engine.run_tokenized(toks,
                                                      pack=self.cfg.pack),
                    trace_id=batch.trace_id)

        self._finish_batch(batch, ack, produce)

    def _observe_age(self, batch: RecordBatch) -> None:
        if batch.created_at is not None:
            from ..state.datamodels import utcnow

            age = (utcnow() - batch.created_at).total_seconds()
            if age >= 0:
                self.m_batch_age.observe(age)
                # Retroactive span so the whole-pipeline age is SLO-
                # evaluable (`--slo-batch-age-ms`): it covers the broker
                # leg queue_wait can't see — the signal that fires when a
                # killed worker's backlog finally lands.
                trace.record("tpu_worker.batch_age", age,
                             trace_id=batch.trace_id,
                             batch=batch.batch_id,
                             worker=self.cfg.worker_id,
                             tenant=batch.tenant)

    @staticmethod
    def _strip_embeddings(results):
        return [{k: v for k, v in r.items() if k != "embedding"}
                for r in results]

    def _commit(self, batch: RecordBatch, results) -> None:
        # Two independent sinks, two independent knobs:
        # publish_embeddings governs the BUS frame (the clustering
        # stage's feed), write_embeddings the JSONL writeback.  They
        # used to be one knob — turning off the JSONL embeddings also
        # silently starved any result-stream consumer.
        batch.results = results if self.cfg.publish_embeddings \
            else self._strip_embeddings(results)
        self.m_batches.inc()
        self.bus.publish(TOPIC_INFERENCE_RESULTS, batch.to_dict())
        if self.provider is not None:
            batch.results = results if self.cfg.write_embeddings \
                else self._strip_embeddings(results)
            self._writeback(batch)

    def _writeback(self, batch: RecordBatch) -> None:
        """Idempotent: one atomically-written file per batch_id, so a bus
        redelivery or worker restart overwrites the same file with the same
        content instead of duplicating rows (SURVEY.md §7 hard part (d))."""
        rel = (f"{self.cfg.storage_prefix}/{batch.crawl_id or 'adhoc'}"
               f"/batches/{batch.batch_id}.jsonl")
        lines = []
        for record, result in zip(batch.records, batch.results):
            lines.append(json.dumps({
                "post_uid": record.get("post_uid", ""),
                "channel_name": record.get("channel_name", ""),
                "batch_id": batch.batch_id,
                "trace_id": batch.trace_id,
                "tenant": batch.tenant,
                **result,
            }, ensure_ascii=False))
        self.provider.put_text(rel, "\n".join(lines) + "\n")

    def warmup(self) -> None:
        """`engine.warmup()` under the stall watchdog.  Bring-up compiles
        are the LONGEST on-chip window (every bucket back-to-back), so a
        chip that wedges here must still hit stall_warn/exit — callers use
        this, not `engine.warmup()`, before serving.  With
        `enable_compilation_cache` configured, restart warmups reload from
        disk and finish in seconds."""
        self._start_watchdog()
        self._step_started = time.monotonic()
        try:
            if self._accepts_pack(getattr(self.engine, "warmup", None)):
                # Warm the path this worker actually serves: with pack on,
                # the packed programs are what live batches dispatch.
                self.engine.warmup(pack=self.cfg.pack)
            else:
                self.engine.warmup()
        finally:
            self._step_started = None
            self._stall_warned = False

    # -- device-stall watchdog ---------------------------------------------
    def _start_watchdog(self) -> None:
        if self._watchdog_started or self._stall_threshold() <= 0:
            return
        self._watchdog_started = True
        t = threading.Thread(target=self._watchdog_loop, daemon=True,
                             name="tpu-watchdog")
        t.start()
        self._threads.append(t)

    def _stall_threshold(self) -> float:
        """Smallest positive stall threshold; 0 when both are disabled.
        An exit-only config (warn 0, exit > 0) still runs the watchdog —
        the hard-exit safety must never silently depend on warnings being
        enabled."""
        positive = [t for t in (self.cfg.stall_warn_s, self.cfg.stall_exit_s)
                    if t > 0]
        return min(positive) if positive else 0.0

    def _watchdog_loop(self) -> None:
        poll = min(5.0, max(0.01, self._stall_threshold() / 10.0))
        while not self._stop.is_set():
            started = self._step_started
            if started is not None:
                age = time.monotonic() - started
                if (self.cfg.stall_warn_s > 0
                        and age >= self.cfg.stall_warn_s
                        and not self._stall_warned):
                    self._stall_warned = True
                    self.m_stalls.inc()
                    flight.record("device_stall",
                                  worker=self.cfg.worker_id,
                                  age_s=round(age, 1))
                    logger.warning(
                        "device step stalled %.0fs (warn threshold %.0fs); "
                        "chip wedged or compile outsized stall_warn_s",
                        age, self.cfg.stall_warn_s,
                        extra={"worker_id": self.cfg.worker_id})
                if self.cfg.stall_exit_s > 0 \
                        and age >= self.cfg.stall_exit_s:
                    logger.critical(
                        "device step stalled %.0fs >= stall_exit_s %.0fs; "
                        "exiting so the supervisor restarts this worker "
                        "(un-acked frames requeue; writeback is idempotent)",
                        age, self.cfg.stall_exit_s,
                        extra={"worker_id": self.cfg.worker_id})
                    # The black-box moment: os._exit skips atexit AND
                    # excepthooks, so the bundle must be written here.
                    flight.dump("stall_exit",
                                error=f"device step stalled {age:.0f}s")
                    import os as _os

                    (self._exit_fn or _os._exit)(17)
                    return  # unreachable in prod; ends the loop under test
            self._stop.wait(poll)

    # -- heartbeats --------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stop.is_set():
            # SLO tick: digest the spans completed since the last beat
            # against the declared budgets (WARN + counter + flight event
            # per breach; no-op with no budgets declared).
            try:
                self._slo.evaluate()
            except Exception as e:  # budget math must never kill the beat
                logger.warning("slo evaluation failed: %s", e)
            status = WORKER_BUSY if not self._queue.empty() else WORKER_IDLE
            msg = StatusMessage.new(
                self.cfg.worker_id, MSG_HEARTBEAT, status,
                tasks_processed=self._processed,
                tasks_success=self._processed - self._errors,
                tasks_error=self._errors,
                uptime_s=time.monotonic() - self._started_at,
                worker_type="tpu")
            msg.queue_length = self._queue.qsize()
            msg.resource_usage = self._telemetry.snapshot()
            # Heartbeat queue depth matches the gauge: the time-weighted
            # mean over the sampler window, next to the instantaneous
            # value (the edge-triggered number scrapes used to alias on).
            msg.resource_usage["queue"] = {
                "depth": self._queue.qsize(),
                "depth_time_weighted": round(self._depth.sample(), 4),
            }
            # Cumulative per-SLO breach counts ride every beat so the
            # orchestrator's watchtower can evaluate burn-rate rules
            # fleet-wide (the fleet_slo_breach_total series).
            slo_snap = self._slo.snapshot()
            msg.resource_usage["slo_breaches"] = slo_snap["breaches"]
            if slo_snap.get("tenant_breaches"):
                msg.resource_usage["tenant_slo_breaches"] = \
                    slo_snap["tenant_breaches"]
            # Per-tenant spend rows (ISSUE 17): the watchtower folds
            # these into the fleet_tenant_* series behind /tenants.
            ledger = self._tenant_ledger()
            if ledger is not None:
                tenants = ledger.snapshot()
                if tenants["rows"]:
                    msg.resource_usage["tenants"] = tenants
            # Self-sample the registry into the rolling store on the
            # same cadence (never raises).
            self._ts_sampler.sample()
            try:
                self.bus.publish(TOPIC_WORKER_STATUS, msg.to_dict())
            except Exception as e:  # bus outage must not kill the worker
                logger.warning("heartbeat publish failed: %s", e)
            self._wait_with_span_exports(self.cfg.heartbeat_s)

    def _wait_with_span_exports(self, wait_s: float) -> None:
        """Sleep until the next heartbeat, firing span exports on their
        OWN cadence in between — a 30 s heartbeat must not stretch a
        15 s span_export_interval_s to 30."""
        deadline = time.monotonic() + wait_s
        interval = self.cfg.span_export_interval_s
        while not self._stop.is_set():
            if interval > 0 and \
                    time.monotonic() - self._last_span_export >= interval:
                self._last_span_export = time.monotonic()
                self.export_spans()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._stop.wait(min(remaining, interval)
                            if interval > 0 else remaining)

    def status(self) -> Dict[str, Any]:
        """Back-compat alias over get_status() (older key names kept)."""
        full = self.get_status()
        return {
            "worker_id": full["worker_id"],
            "queue_depth": full["queue_depth"],
            "processed": full["processed_batches"],
            "errors": full["error_batches"],
            "uptime_s": full["uptime_s"],
        }
