"""Model checkpoint save/restore (orbax).

The crawl side's checkpoint/resume lives in the state layer (SURVEY.md §5.4);
this is the model-side counterpart: params (and optionally optimizer state)
persisted per step so a fine-tune or a long inference deployment resumes
exactly.  Orbax handles sharded arrays natively, so a checkpoint written from
an 8-chip mesh restores onto any other mesh shape.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def save_params(path: str, params: Any, force: bool = True) -> None:
    """Write a param pytree checkpoint to ``path`` (a directory)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params, force=force)


def load_params(path: str, like: Optional[Any] = None) -> Any:
    """Restore a param pytree; ``like`` (an abstract or concrete pytree)
    drives dtype/sharding of the restored arrays when given."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(os.path.abspath(path), like)
        return ckptr.restore(os.path.abspath(path))


def _indexed_dirs(root: str, prefix: str) -> list:
    """All ``{prefix}N`` subdirectories of ``root`` as (N, path), sorted."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if name.startswith(prefix):
            try:
                out.append((int(name[len(prefix):]),
                            os.path.join(root, name)))
            except ValueError:
                continue
    return sorted(out)


def latest_step_dir(root: str) -> Optional[str]:
    """Newest step_N subdirectory under a checkpoint root, or None."""
    dirs = _indexed_dirs(root, "step_")
    return dirs[-1][1] if dirs else None


def save_train_state(root: str, epoch: int, params: Any, opt_state: Any,
                     history: Any) -> str:
    """Persist a full TRAINING state (params + optimizer state + history)
    as ``{root}/epoch_N`` — what a resumable fine-tune needs beyond the
    serving checkpoint's bare params.  Returns the written directory.

    ``history.json`` is written LAST and doubles as the completion
    marker: a crash mid-save leaves a dir `latest_train_state` skips.
    Older complete epochs are pruned after a successful save (only the
    newest is ever read; a 10-epoch encoder fine-tune would otherwise
    hold 10 copies of params + AdamW moments)."""
    import json
    import shutil

    import orbax.checkpoint as ocp

    path = os.path.abspath(os.path.join(root, f"epoch_{epoch}"))
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"params": params, "opt_state": opt_state},
                   force=True)
    # History is tiny host-side JSON; sidecar file keeps the orbax tree
    # purely numeric.
    tmp = os.path.join(path, "history.json.tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"epoch": epoch, "history": history}, f)
    os.replace(tmp, os.path.join(path, "history.json"))
    for n, older in _indexed_dirs(os.path.abspath(root), "epoch_"):
        if n < epoch:
            shutil.rmtree(older, ignore_errors=True)
    return path


def latest_train_state(root: str) -> Optional[str]:
    """Newest COMPLETE epoch_N directory under a train-state root, or
    None.  Dirs without the history.json completion marker (a crash
    between the orbax commit and the marker write) are skipped, falling
    back to the previous complete epoch."""
    for _, path in reversed(_indexed_dirs(root, "epoch_")):
        if os.path.exists(os.path.join(path, "history.json")):
            return path
    return None


def load_train_state(path: str, like_params: Any, like_opt_state: Any
                     ) -> tuple:
    """Restore ``(epoch, params, opt_state, history)`` from an epoch dir
    written by `save_train_state`; the ``like_*`` trees drive structure/
    dtype restoration (optax states are namedtuple pytrees orbax cannot
    rebuild without a donor)."""
    import json

    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(os.path.abspath(path),
                             {"params": like_params,
                              "opt_state": like_opt_state})
    with open(os.path.join(path, "history.json"), encoding="utf-8") as f:
        meta = json.load(f)
    return (int(meta["epoch"]), tree["params"], tree["opt_state"],
            list(meta["history"]))
