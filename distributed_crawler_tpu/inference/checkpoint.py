"""Model checkpoint save/restore (orbax).

The crawl side's checkpoint/resume lives in the state layer (SURVEY.md §5.4);
this is the model-side counterpart: params (and optionally optimizer state)
persisted per step so a fine-tune or a long inference deployment resumes
exactly.  Orbax handles sharded arrays natively, so a checkpoint written from
an 8-chip mesh restores onto any other mesh shape.
"""

from __future__ import annotations

import os
from typing import Any, Optional


def save_params(path: str, params: Any, force: bool = True) -> None:
    """Write a param pytree checkpoint to ``path`` (a directory)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), params, force=force)


def load_params(path: str, like: Optional[Any] = None) -> Any:
    """Restore a param pytree; ``like`` (an abstract or concrete pytree)
    drives dtype/sharding of the restored arrays when given."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(os.path.abspath(path), like)
        return ckptr.restore(os.path.abspath(path))


def latest_step_dir(root: str) -> Optional[str]:
    """Newest step_N subdirectory under a checkpoint root, or None."""
    if not os.path.isdir(root):
        return None
    steps = []
    for name in os.listdir(root):
        if name.startswith("step_"):
            try:
                steps.append((int(name.split("_", 1)[1]), name))
            except ValueError:
                continue
    if not steps:
        return None
    return os.path.join(root, max(steps)[1])
