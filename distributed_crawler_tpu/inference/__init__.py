"""TPU inference worker: the ⟨NEW⟩ stage grafted onto the crawl pipeline.

SURVEY.md §7.6: a JAX/Flax service consuming record batches off the bus —
tokenize → pad to buckets → jit'd embed (multilingual-E5) + classify (XLM-R)
on a device mesh — writing results back via the state providers.  The module
split mirrors the data path:

- :mod:`tokenizer` — host-side text → ids (hashing tokenizer by default;
  any callable with the same signature plugs in).
- :mod:`engine` — device half: bucketed compile cache, mesh sharding,
  fused embed+classify step.
- :mod:`worker` — service half: bus subscription, double-buffered feed,
  result writeback, heartbeats, metrics.
- :mod:`checkpoint` — orbax param save/restore.
"""

from .bridge import InferenceBridge
from .tokenizer import HashingTokenizer, Tokenizer
from .engine import EngineConfig, InferenceEngine
from .worker import TPUWorker, TPUWorkerConfig

__all__ = [
    "InferenceBridge",
    "Tokenizer",
    "HashingTokenizer",
    "EngineConfig",
    "InferenceEngine",
    "TPUWorker",
    "TPUWorkerConfig",
]
