"""Inference engine: the device half of the TPU worker.

Owns the model, its (possibly mesh-sharded) params, and a per-bucket compile
cache: every (bucket, batch_size) pair compiles exactly once and is reused —
the host side quantizes ragged crawl text into those shapes (`ops.padding`),
so XLA never sees a dynamic dimension.  The flagship op is the fused
embed+classify pass (one encoder traversal for both outputs).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..models.encoder import (
    E5_BASE,
    E5_LARGE,
    E5_SMALL,
    EmbedderClassifier,
    EncoderConfig,
    TINY_TEST,
    XLMR_BASE,
)
from ..ops.padding import (
    DEFAULT_MAX_SEGMENTS_PER_ROW,
    BucketSpec,
    bucket_for,
    pack_batch,
    pack_rows,
)
from ..utils import trace
from ..utils.costmodel import (
    CostModel,
    EfficiencyMeter,
    encoder_forward_flops,
)
from ..utils.metrics import REGISTRY, MetricsRegistry
from ..utils.occupancy import DeviceTimeline
from .tokenizer import HashingTokenizer, Tokenizer

MODEL_REGISTRY: Dict[str, EncoderConfig] = {
    "e5_small": E5_SMALL,
    "e5_base": E5_BASE,
    "e5_large": E5_LARGE,
    "xlmr_base": XLMR_BASE,
    "tiny": TINY_TEST,
}


@dataclass(frozen=True)
class EngineConfig:
    model: str = "e5_small"
    n_labels: int = 8
    batch_size: int = 256
    buckets: tuple = (32, 64, 128, 256, 512)
    seed: int = 0
    # Local HF checkpoint dir (model.safetensors/pytorch_model.bin +
    # config.json [+ tokenizer.json]): loads REAL weights + vocab instead of
    # the registry config with random init.  Offline by design.
    pretrained_dir: Optional[str] = None
    # Orbax checkpoint dir written by `dct --mode train-head` (or
    # checkpoint.save_params): restored OVER whatever params the engine
    # otherwise starts from, closing the crawl→train→serve loop.  Points at
    # either a step_N directory or a root containing them (latest wins).
    checkpoint_dir: Optional[str] = None
    # Inference-time parameter dtype. None keeps params as loaded (f32 —
    # the training layout); "bfloat16" casts float params once at startup,
    # halving weight HBM traffic per step.  Serving-only: never persist
    # bf16-cast params back into a training checkpoint.
    param_dtype: Optional[str] = None
    # "int8": quantize the projection GEMMs at startup and run them
    # int8×int8→int32 on the MXU (2× bf16 peak on v5e; see ops/quant.py).
    # Applies over whatever params were loaded (random / pretrained /
    # checkpoint); the float source tree is discarded after conversion.
    quantize: Optional[str] = None
    # Attention dispatch: "auto" (ops/attention.py policy: Pallas flash
    # past FLASH_MIN_SEQ on TPU, XLA otherwise) | "xla" | "flash".
    attention: Optional[str] = None
    # Switch-MoE dispatch override for MoE models: None keeps the
    # model's own setting; "dense" | "capacity" force a path (capacity =
    # Switch static-slot packing, ~cf× MLP FLOPs instead of n_experts×;
    # rejected with int8 quantize by EncoderConfig.validate()).
    moe_dispatch: Optional[str] = None
    # Per-row segment bound for `run_tokenized(..., pack=True)`: packed
    # results come back as a static [batch, pack_max_segments] block, so
    # this is a compiled shape, not a heuristic.  One packed program per
    # bucket (the segment-id/position operands), never per fill level.
    pack_max_segments: int = DEFAULT_MAX_SEGMENTS_PER_ROW

    def encoder_config(self) -> EncoderConfig:
        try:
            base = MODEL_REGISTRY[self.model]
        except KeyError:
            raise ValueError(
                f"unknown model {self.model!r}; "
                f"one of {sorted(MODEL_REGISTRY)}") from None
        return replace(base, n_labels=self.n_labels)


def enable_compilation_cache(cache_dir: str,
                             min_compile_time_s: float = 1.0) -> bool:
    """Turn on jax's persistent compilation cache rooted at ``cache_dir``.

    Serving restarts — including the stall watchdog's hard-exit/restart
    cycle (`worker.py`) and rolling redeploys — then reload each
    (bucket, batch) program from disk instead of paying the 20-40 s XLA
    compile per bucket.  Programs below ``min_compile_time_s`` are not
    persisted (they recompile faster than they deserialize).  Best-effort:
    returns False (with a log line) on jax versions without the config
    knobs rather than failing startup.
    """
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_time_s)
        # Cache every hit regardless of entry size — serving programs are
        # few and the directory is operator-owned.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        return True
    except Exception as e:  # pragma: no cover - version-dependent
        logging.getLogger(__name__).warning(
            "persistent compilation cache unavailable: %s", e)
        return False


class InferenceEngine:
    """Tokenize → bucket → jit'd fused embed+classify → host results.

    ``mesh`` is optional: None runs single-device (standalone mode's analog);
    with a mesh, params and batches are sharded per `parallel.sharding` and
    the same jitted step scales data-parallel over dp (SURVEY.md §2.3.1).
    """

    def __init__(self, cfg: EngineConfig,
                 mesh=None,
                 params: Optional[Any] = None,
                 tokenizer: Optional[Tokenizer] = None,
                 registry: MetricsRegistry = REGISTRY):
        import jax

        self.cfg = cfg
        if cfg.attention and cfg.attention not in ("auto", "xla", "flash"):
            # Validate BEFORE any checkpoint I/O: a typo must not cost a
            # multi-GB pretrained load first.
            raise ValueError(f"unknown attention mode {cfg.attention!r}")
        if cfg.moe_dispatch and cfg.moe_dispatch not in ("dense",
                                                         "capacity"):
            raise ValueError(f"unknown moe_dispatch {cfg.moe_dispatch!r}")
        if cfg.moe_dispatch == "capacity" and cfg.quantize:
            # Decidable from the config alone — don't pay checkpoint load
            # + calibration + quantization before the conflict surfaces.
            raise ValueError(
                "moe_dispatch='capacity' requires quantize unset — the "
                "int8 expert GEMMs ride dense dispatch")
        if cfg.pretrained_dir:
            self.ecfg, params, tokenizer = _load_pretrained(
                cfg, params, tokenizer)
        else:
            self.ecfg = cfg.encoder_config()
        if cfg.attention:
            # Applied HERE so every param source — registry, pretrained
            # checkpoint, restored head — honors it.
            self.ecfg = replace(self.ecfg, attention=cfg.attention)
        if cfg.moe_dispatch:
            self.ecfg = replace(self.ecfg, moe_dispatch=cfg.moe_dispatch)
        self.label_names: Optional[List[str]] = None
        if cfg.checkpoint_dir:
            # The checkpoint's own head width wins (a 2-class fine-tune must
            # not be forced through the engine's default n_labels): restore
            # shapes from disk, then size the model to match.
            params = self._restore_checkpoint(cfg.checkpoint_dir)
            head = params["params"]["cls_head"]
            pooler_in = int(head["pooler"]["kernel"].shape[0])
            if pooler_in != self.ecfg.hidden:
                raise ValueError(
                    f"checkpoint at {cfg.checkpoint_dir} was trained on a "
                    f"hidden={pooler_in} encoder but the engine model "
                    f"{cfg.model!r} has hidden={self.ecfg.hidden}")
            self.ecfg = replace(
                self.ecfg, n_labels=int(head["head"]["bias"].shape[0]))
        self.mesh = mesh
        # Mesh accounting: how many chips one dispatch covers, the data
        # axis size, and the padded ROW dimension.  Rows round UP to a
        # multiple of the DATA axis (the only axis sharding the batch
        # dim; dp == n_devices under the pure-dp serving default) so a
        # non-divisible batch_size (or a non-divisible coalesced group's
        # tail chunk) still dispatches ONE program with the batch dim
        # sharded over dp — without the padding,
        # `parallel.sharding.shard_batch` would silently fall back to
        # replicated placement and every chip would run the full batch.
        # An sp/tp-dominated mesh (dp < n_devices) pads only to dp:
        # those axes impose no row-divisibility constraint, and padding
        # further would dispatch pure waste.  Padding rows are all-pad (mask 0): they are excluded
        # from results, writeback, and the real-token side of the
        # goodput/density meters, and they COUNT as dispatched slot
        # tokens — padded work is real waste and must read as such.
        if mesh is not None:
            self.n_devices = int(mesh.devices.size)
            self._dp = int(mesh.shape.get("dp", 1))
            self._device_labels = [str(d.id) for d in mesh.devices.flat]
        else:
            self.n_devices = 1
            self._dp = 1
            self._device_labels = ["0"]
        self._rows = -(-cfg.batch_size // self._dp) * self._dp
        self.model = EmbedderClassifier(self.ecfg)
        self.tokenizer = tokenizer or HashingTokenizer(self.ecfg.vocab_size)
        self.bucket_spec = BucketSpec(
            tuple(b for b in cfg.buckets if b <= self.ecfg.max_len))
        self._steps: Dict[int, Any] = {}  # bucket -> jitted fn
        self._packed_steps: Dict[int, Any] = {}  # bucket -> jitted packed fn
        self.m_latency = registry.histogram(
            "tpu_inference_batch_seconds",
            "batch dispatch->results-on-host latency (pipelined: the "
            "window also spans the NEXT batch's host-side pack/dispatch, "
            "which overlaps this batch's device time)")
        self.m_posts = registry.counter(
            "tpu_inference_posts_total", "posts through embed+classify")
        self.m_padding = registry.counter(
            "tpu_inference_pad_slots_total", "wasted pad slots")
        self.m_packed = registry.counter(
            "tpu_inference_packed_segments_total",
            "sequences served through packed bucket rows")
        # Labeled by padding bucket: the per-bucket split of m_posts, so a
        # stream drifting into oversized buckets is visible on /metrics
        # instead of only as a padding-counter creep.
        self.m_bucket_posts = registry.counter(
            "tpu_inference_bucket_posts_total",
            "posts through embed+classify per padding bucket")
        # A miss = first dispatch of a (bucket, path) program in this
        # process (XLA compiles on that first call).  Serving steady-state
        # should see this flat after warmup; a moving counter means live
        # batches are paying compiles.
        self.m_compile_miss = registry.counter(
            "tpu_engine_compile_cache_misses_total",
            "jit program builds by bucket and path (first-dispatch "
            "compiles)")
        # Hardware-efficiency accounting (`utils/costmodel.py`): per-bucket
        # compiled cost captured at each program's first dispatch, and a
        # rolling goodput/MFU meter fed per device batch.  Both serve the
        # /costs endpoint via cost_snapshot(); the meter also rides
        # telemetry heartbeats into the orchestrator's /cluster view.
        self.costs = CostModel(registry=registry)
        # Mesh-aware: peak resolves as the n_devices aggregate (MFU must
        # not read N× too high when a mesh appears) and per-dispatch
        # shard masks feed the per-chip goodput rows.
        self.meter = EfficiencyMeter(registry=registry,
                                     n_devices=self.n_devices,
                                     device_labels=self._device_labels)
        # Device-occupancy accounting (`utils/occupancy.py`): one interval
        # per device batch, [async dispatch, readback-on-host] — the
        # host-observable envelope of device busy time.  Derives the
        # busy-fraction / overlap-fraction gauges and the pipeline-bubble
        # counter the host spans cannot express (the one-deep pipeline
        # makes every host window contain the NEXT batch's pack).  Within
        # one run_tokenized call readback i structurally outlasts dispatch
        # i+1, so bubbles only open BETWEEN calls — the serial
        # tokenize→dispatch→wait gap per coalesce group; the worker's feed
        # loop calls `timeline.start_stream()` whenever its queue ran dry
        # so idle-by-no-work never scores as a bubble.
        self.timeline = DeviceTimeline(registry=registry, path="text",
                                       n_devices=self.n_devices)

        if params is None:
            import jax.numpy as jnp

            probe = max(32, self.bucket_spec.lengths[0])
            ids = jnp.zeros((1, probe), jnp.int32)
            mask = jnp.ones((1, probe), jnp.bool_)
            params = self.model.init(jax.random.PRNGKey(cfg.seed), ids, mask)
        if cfg.param_dtype:
            import jax.numpy as jnp

            target = jnp.dtype(cfg.param_dtype)
            params = jax.tree.map(
                lambda x: x.astype(target)
                if hasattr(x, "dtype") and x.dtype == jnp.float32 else x,
                params)
        if cfg.quantize:
            if cfg.quantize not in ("int8", "int8_static"):
                raise ValueError(f"unknown quantize mode {cfg.quantize!r}")
            from ..models.quant import quantize_encoder_params

            act_scales = None
            if cfg.quantize == "int8_static":
                # Calibrate per-projection activation scales on one float
                # forward over a token batch drawn from the tokenizer's id
                # range (operators wanting text-matched scales can warm the
                # float engine first and pass a checkpoint; abs-max over a
                # wide random batch is a serviceable default because the
                # encoder's LN-bounded activations vary little with input).
                import jax as _jax
                import jax.numpy as jnp

                from ..models.quant import calibrate_activation_scales

                probe_len = self.bucket_spec.lengths[-1]
                probe_ids = _jax.random.randint(
                    _jax.random.PRNGKey(cfg.seed + 1),
                    (min(cfg.batch_size, 64), probe_len), 0,
                    self.ecfg.vocab_size)
                probe_mask = jnp.ones_like(probe_ids, dtype=jnp.bool_)
                calib_model = EmbedderClassifier(
                    replace(self.ecfg, calibrate=True))
                act_scales = calibrate_activation_scales(
                    calib_model, params, probe_ids, probe_mask)
            params = quantize_encoder_params(params, act_scales=act_scales)
            self.ecfg = replace(self.ecfg, quant=cfg.quantize)
            self.ecfg.validate()
            self.model = EmbedderClassifier(self.ecfg)
        if mesh is not None:
            from ..parallel.sharding import shard_params

            params = shard_params(params, mesh)
        self.params = params

    def _restore_checkpoint(self, root: str):
        """Restore fine-tuned params (and the label vocabulary, if the
        trainer saved one) with shapes taken from the checkpoint itself."""
        import json
        import os

        from .checkpoint import latest_step_dir, load_params

        path = latest_step_dir(root) or root
        params = _migrate_split_qkv(load_params(path))
        for cand in (os.path.join(root, "labels.json"),
                     os.path.join(path, "labels.json")):
            if os.path.exists(cand):
                with open(cand, "r", encoding="utf-8") as f:
                    self.label_names = json.load(f)["labels"]
                break
        return params

    # -- device step -------------------------------------------------------
    def _step(self, bucket: int):
        import jax

        fn = self._steps.get(bucket)
        if fn is None:
            self.m_compile_miss.labels(bucket=str(bucket),
                                       path="unpacked").inc()
            fn = jax.jit(lambda p, i, m: self.model.apply(p, i, m))
            self._steps[bucket] = fn
        return fn

    def _packed_step(self, bucket: int):
        import jax

        fn = self._packed_steps.get(bucket)
        if fn is None:
            self.m_compile_miss.labels(bucket=str(bucket),
                                       path="packed").inc()
            n_seg = self.cfg.pack_max_segments
            # n_seg closes over as a static: the only new program per
            # bucket is this one (the segment-id/position operands); every
            # fill level reuses it because the shapes never change.
            fn = jax.jit(lambda p, i, m, seg, pos: self.model.apply(
                p, i, m, segment_ids=seg, positions=pos, n_segments=n_seg))
            self._packed_steps[bucket] = fn
        return fn

    def compile_cache_stats(self) -> Dict[str, Any]:
        """Compile-cache state for telemetry heartbeats
        (`utils/telemetry.py`): which (bucket, path) programs exist and the
        cumulative first-dispatch miss count.  The emitter turns
        ``misses_total`` into per-heartbeat deltas — steady-state serving
        should report delta 0; anything else means live batches paid XLA
        compiles."""
        misses: Dict[str, float] = {}
        total = 0.0
        for labels, value in self.m_compile_miss.series():
            if not labels:
                continue  # unlabeled parent: never incremented
            misses[f"{labels.get('path', '?')}:"
                   f"{labels.get('bucket', '?')}"] = value
            total += value

        def keys(d: Dict[int, Any]) -> list:
            # The heartbeat thread reads while the feed thread inserts a
            # freshly-compiled bucket; retry the rare mid-insert snapshot
            # instead of degrading the whole telemetry beat.
            for _ in range(3):
                try:
                    return sorted(d)
                except RuntimeError:
                    continue
            return []

        return {
            "programs_unpacked": keys(self._steps),
            "programs_packed": keys(self._packed_steps),
            "misses_total": total,
            "misses": misses,
        }

    def _capture_cost(self, bucket: int, path: str, step, placed) -> None:
        """Cost-model capture on a program's FIRST dispatch: the call that
        just ran paid the XLA compile, so ``step.lower(...)`` here is
        tracing-only and ``cost_analysis()`` reads the program the worker
        actually serves.  Idempotent and never raises (`CostModel`)."""
        if self.costs.has(bucket, path):
            return
        rows = self._rows
        self.costs.capture(
            bucket, path, lambda: step.lower(self.params, *placed),
            encoder_forward_flops(self.ecfg, rows, bucket),
            batch=rows, seq=bucket)

    def _batch_flops(self, bucket: int, path: str) -> float:
        # The dispatched program's row dim is `_rows` (batch_size padded
        # to a mesh multiple), so the analytic fallback prices what the
        # mesh actually runs, not the logical batch_size.
        return self.costs.flops_for(
            bucket, path,
            default=encoder_forward_flops(self.ecfg, self._rows, bucket))

    def _per_device_real(self, mask: np.ndarray) -> Optional[List[int]]:
        """Real (non-pad) tokens per mesh device, from the host-side mask
        BEFORE device_put: the padded batch dim shards contiguously over
        dp, so chip i's data shard is one row block — the split that
        makes per-chip goodput honest (a tail chunk's padding rows land
        in the high shards and score zero there).  With sp/tp > 1, each
        device in a dp slice reports its shard's tokens (they all touch
        that shard).  None single-device or on a replicated fallback."""
        if self.mesh is None or self.n_devices <= 1:
            return None
        rows = mask.shape[0]
        if rows % self._dp:
            return None  # shard_batch replicates this shape; no split
        per_shard = np.asarray(mask, dtype=np.int64).reshape(
            self._dp, rows // self._dp, -1).sum(axis=(1, 2))
        spt = self.n_devices // self._dp
        return [int(per_shard[i // spt]) for i in range(self.n_devices)]

    def cost_snapshot(self) -> Dict[str, Any]:
        """The /costs body: per-(bucket, path) compiled cost + the rolling
        efficiency window (`utils/metrics.set_costs_provider` seam)."""
        return {
            "model": self.cfg.model,
            "batch_size": self.cfg.batch_size,
            "rows_per_dispatch": self._rows,
            "n_devices": self.n_devices,
            "mesh": {str(k): int(v) for k, v in self.mesh.shape.items()}
            if self.mesh is not None else None,
            "buckets": list(self.bucket_spec.lengths),
            "costs": self.costs.snapshot(),
            "efficiency": self.meter.snapshot(),
            "occupancy": self.timeline.snapshot(),
        }

    def efficiency_snapshot(self) -> Dict[str, Any]:
        """Rolling MFU/goodput map for telemetry heartbeats
        (`utils/telemetry.py`); {} until the first batch lands."""
        return self.meter.snapshot()

    def occupancy_snapshot(self) -> Dict[str, Any]:
        """Device-occupancy map for telemetry heartbeats — ALSO the
        refresh driving the busy/overlap gauges between /costs scrapes
        (record() stays O(1) on the serving path by design)."""
        return self.timeline.snapshot()

    def _place(self, ids: np.ndarray, mask: np.ndarray, *extra: np.ndarray):
        import jax.numpy as jnp

        arrs = tuple(jnp.asarray(a) for a in (ids, mask) + extra)
        if self.mesh is not None:
            from ..parallel.sharding import shard_batch

            arrs = shard_batch(arrs, self.mesh)  # tree-maps the tuple
        return arrs

    # -- public API --------------------------------------------------------
    def run_tokenized(self, token_lists: Sequence[List[int]],
                      pack: bool = False) -> List[Dict[str, Any]]:
        """Embed+classify pre-tokenized sequences; results in input order.

        One-deep software pipeline: jax dispatch is async, so batch i+1 is
        packed and dispatched BEFORE batch i's device→host readback — the
        device computes while the host materializes/post-processes, and
        the per-batch RPC readback latency (the dominant cost through a
        tunneled chip: ~90 ms vs ~24 ms of compute at batch 256) overlaps
        compute instead of serializing with it.

        ``pack=True`` routes through the packed path: several short
        sequences share one bucket row behind segment-aware attention
        masks, so short-text streams stop paying MXU/HBM for pad tokens.
        Prefer ``pack=False`` for long-sequence-dominated streams (rows
        near their bucket length pack 1:1 and only pay the extra operand).

        Every call runs under an ``engine.run_tokenized`` span with
        per-stage children (pack / device_put / compute / unpack) — inside
        an ambient trace (the TPU worker's) they join it; standalone calls
        root a fresh trace so /traces still shows the stage breakdown.
        Note the pipeline when reading spans: ``engine.compute`` is the
        async dispatch, and the device time it starts overlaps the NEXT
        chunk's pack/dispatch; the blocking device→host readback is
        ``engine.unpack``.
        """
        with trace.span("engine.run_tokenized",
                        sequences=len(token_lists), pack=bool(pack)):
            if any(not t for t in token_lists):
                return self._run_with_empties(token_lists, pack)
            if pack:
                return self._run_packed(token_lists)
            return self._run_unpacked(token_lists)

    def _run_unpacked(self, token_lists: Sequence[List[int]]
                      ) -> List[Dict[str, Any]]:
        results: List[Optional[Dict[str, Any]]] = [None] * len(token_lists)
        groups: Dict[int, List[int]] = {}
        for i, toks in enumerate(token_lists):
            groups.setdefault(
                bucket_for(len(toks), self.bucket_spec), []).append(i)

        # Chunk by the PADDED row dim (batch_size rounded up to a
        # data-axis multiple): padding rows keep the dp sharding
        # divisible; they
        # carry mask 0 and no chunk entry, so they never reach results,
        # writeback, or the real-token meters — but they DO count as
        # dispatched slot tokens (honest padding density).
        rows = self._rows
        pending: Optional[tuple] = None  # (chunk, emb_dev, logits_dev, t0,
        #                                  bucket, real_tokens, per_dev)

        def materialize(chunk, emb, logits, t0, bucket, real_tokens,
                        per_dev):
            with trace.span("engine.unpack", rows=len(chunk)):
                emb_np = np.asarray(emb)         # device->host sync
                logits_np = np.asarray(logits)
                # Histogram semantics: dispatch→results-on-host per batch.
                # Under the pipeline this window ALSO contains the next
                # batch's host-side pack+dispatch (which overlapped this
                # batch's device time) — see the metric's help text.
                dt = time.perf_counter() - t0
                self.timeline.record(t0, t0 + dt)
                self.m_latency.observe(dt)
                self.meter.record(dt, self._batch_flops(bucket, "unpacked"),
                                  real_tokens, rows * bucket,
                                  per_device_real_tokens=per_dev)
                self.m_posts.inc(len(chunk))
                self.m_padding.inc(rows - len(chunk))
                scores = _softmax_np(logits_np)
                for row, i in enumerate(chunk):
                    label = int(np.argmax(logits_np[row]))
                    results[i] = {
                        "embedding": emb_np[row].tolist(),
                        "label": label,
                        "scores": scores[row].tolist(),
                    }
                    if self.label_names and label < len(self.label_names):
                        results[i]["label_name"] = self.label_names[label]

        for bucket, indices in sorted(groups.items()):
            for start in range(0, len(indices), rows):
                chunk = indices[start:start + rows]
                self.m_bucket_posts.labels(bucket=str(bucket)).inc(len(chunk))
                with trace.span("engine.pack", bucket=bucket,
                                rows=len(chunk)):
                    ids, mask = pack_batch(
                        [token_lists[i] for i in chunk],
                        BucketSpec((bucket,)), batch_pad_to=rows)
                real_tokens = int(mask.sum())
                per_dev = self._per_device_real(mask)
                with trace.span("engine.device_put", bucket=bucket):
                    placed = self._place(ids, mask)
                step = self._step(bucket)
                t0 = time.perf_counter()
                with trace.span("engine.compute", bucket=bucket, batch=rows,
                                sequences=len(chunk)):
                    emb, logits = step(self.params, *placed)
                self._capture_cost(bucket, "unpacked", step, placed)
                if pending is not None:
                    materialize(*pending)
                pending = (chunk, emb, logits, t0, bucket, real_tokens,
                           per_dev)
        if pending is not None:
            materialize(*pending)
        return results  # type: ignore[return-value]

    def _run_with_empties(self, token_lists: Sequence[List[int]],
                          pack: bool) -> List[Dict[str, Any]]:
        """Canonical host-side result for EMPTY token lists, identical in
        both paths: zero embedding, uniform scores, label 0.  Classifying
        nothing on device was never meaningful (the unpacked path used to
        classify a pad row's position-0 state; the packed path's empty
        segment pools to zero) — pinning one answer here keeps the
        packed-equals-unpacked contract total."""
        sub = [t for t in token_lists if t]
        it = iter(self.run_tokenized(sub, pack=pack) if sub else [])
        uniform = [1.0 / self.ecfg.n_labels] * self.ecfg.n_labels
        out: List[Dict[str, Any]] = []
        for t in token_lists:
            if t:
                out.append(next(it))
            else:
                r: Dict[str, Any] = {
                    "embedding": [0.0] * self.ecfg.hidden,
                    "label": 0, "scores": list(uniform)}
                if self.label_names:
                    r["label_name"] = self.label_names[0]
                out.append(r)
        return out

    def _run_packed(self, token_lists: Sequence[List[int]]
                    ) -> List[Dict[str, Any]]:
        """Packed twin of the dispatch loop: per bucket, first-fit-pack the
        sequences into shared rows (`ops/padding.pack_rows`), run the same
        static [batch, bucket] shapes (plus segment-id/position operands)
        through the one-deep pipeline, and fan per-segment results back to
        input order via the packer's (row, slot) assignments."""
        results: List[Optional[Dict[str, Any]]] = [None] * len(token_lists)
        groups: Dict[int, List[int]] = {}
        for i, toks in enumerate(token_lists):
            groups.setdefault(
                bucket_for(len(toks), self.bucket_spec), []).append(i)

        # Padded row dim, as in the unpacked path: a coalesced group
        # whose packed rows don't divide by the data axis still
        # dispatches one program (all-pad filler rows, mask 0, no slot),
        # sharded over dp instead of silently replicated.
        rows = self._rows
        pending: Optional[tuple] = None  # (slots, used, emb, logits, t0,
        #                                  bucket, real_tokens, per_dev)

        def materialize(slots, used_rows, emb, logits, t0, bucket,
                        real_tokens, per_dev):
            with trace.span("engine.unpack", segments=len(slots),
                            rows=used_rows):
                emb_np = np.asarray(emb)        # device->host sync
                logits_np = np.asarray(logits)  # [rows, S, n_labels]
                dt = time.perf_counter() - t0
                self.timeline.record(t0, t0 + dt)
                self.m_latency.observe(dt)
                self.meter.record(dt, self._batch_flops(bucket, "packed"),
                                  real_tokens, rows * bucket,
                                  per_device_real_tokens=per_dev)
                self.m_posts.inc(len(slots))
                self.m_packed.inc(len(slots))
                self.m_padding.inc(rows - used_rows)
                flat = logits_np.reshape(-1, logits_np.shape[-1])
                scores = _softmax_np(flat).reshape(logits_np.shape)
                for row, slot, i in slots:
                    label = int(np.argmax(logits_np[row, slot]))
                    results[i] = {
                        "embedding": emb_np[row, slot].tolist(),
                        "label": label,
                        "scores": scores[row, slot].tolist(),
                    }
                    if self.label_names and label < len(self.label_names):
                        results[i]["label_name"] = self.label_names[label]

        for bucket, indices in sorted(groups.items()):
            self.m_bucket_posts.labels(bucket=str(bucket)).inc(len(indices))
            with trace.span("engine.pack", bucket=bucket,
                            sequences=len(indices), packed=True):
                packed = pack_rows([token_lists[i] for i in indices], bucket,
                                   max_segments=self.cfg.pack_max_segments,
                                   indices=indices)
            for start in range(0, packed.n_rows, rows):
                end = min(start + rows, packed.n_rows)
                used = end - start
                ids = packed.ids[start:end]
                mask = packed.mask[start:end]
                seg = packed.segment_ids[start:end]
                pos = packed.positions[start:end]
                if used < rows:
                    # All-pad filler rows (segment id 0 everywhere) keep
                    # the batch shape static; no slot maps to them.
                    pad = ((0, rows - used), (0, 0))
                    ids = np.pad(ids, pad)
                    mask = np.pad(mask, pad)
                    seg = np.pad(seg, pad)
                    pos = np.pad(pos, pad)
                slots = [(r - start, s, orig)
                         for r in range(start, end)
                         for s, orig in enumerate(packed.assignments[r])]
                real_tokens = int(mask.sum())
                per_dev = self._per_device_real(mask)
                with trace.span("engine.device_put", bucket=bucket,
                                packed=True):
                    placed = self._place(ids, mask, seg, pos)
                step = self._packed_step(bucket)
                t0 = time.perf_counter()
                with trace.span("engine.compute", bucket=bucket, batch=rows,
                                segments=len(slots), packed=True):
                    emb, logits = step(self.params, *placed)
                self._capture_cost(bucket, "packed", step, placed)
                if pending is not None:
                    materialize(*pending)
                pending = (slots, used, emb, logits, t0, bucket,
                           real_tokens, per_dev)
        if pending is not None:
            materialize(*pending)
        return results  # type: ignore[return-value]

    def run(self, texts: Sequence[str],
            pack: bool = False) -> List[Dict[str, Any]]:
        with trace.span("engine.run", texts=len(texts), pack=bool(pack)):
            with trace.span("engine.tokenize", texts=len(texts)):
                toks = self.tokenizer.encode_batch(texts)
            return self.run_tokenized(toks, pack=pack)

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        out = self.run(texts)
        return np.asarray([r["embedding"] for r in out], dtype=np.float32)

    def warmup(self, buckets: Optional[Sequence[int]] = None,
               pack: Optional[bool] = None) -> None:
        """Pre-compile the (bucket, batch) programs before serving.

        ``pack`` picks which path to warm: True = the packed programs
        (what a pack-serving worker actually dispatches), False = the
        unpacked ones, None = both.  A pack-serving deployment that only
        warmed the unpacked path would pay its first XLA compiles inside
        live batches — under the stall watchdog."""
        modes = (False, True) if pack is None else (bool(pack),)
        for b in buckets or self.bucket_spec.lengths:
            toks = ([[1, 2, 3]] * min(2, self.cfg.batch_size)
                    if b == self.bucket_spec.lengths[0]
                    else [[1] * (b - 1)])
            for m in modes:
                self.run_tokenized(toks, pack=m)
        # Warmup intervals are compile-dominated: drop them so the
        # occupancy window starts clean for live serving.
        self.timeline.reset()


def _load_pretrained(cfg: EngineConfig, params, tokenizer):
    """Resolve (ecfg, params, tokenizer) from a local HF checkpoint dir.

    Classification checkpoints load fully; encoder-only checkpoints (E5)
    get their trained encoder plus a fresh head initialized at ``seed`` —
    embeddings are real, labels need fine-tuning (`models/train.py`).
    """
    from ..models.hf_convert import load_hf_encoder

    path = cfg.pretrained_dir
    assert path is not None
    try:
        # n_labels=None: the checkpoint's own head width wins over the
        # engine default — a trained 3-way head must not be reshaped to 8.
        ecfg, loaded = load_hf_encoder(path, arch="embedder_classifier",
                                       n_labels=None)
    except ValueError:
        import jax
        import jax.numpy as jnp

        ecfg, loaded = load_hf_encoder(path, arch="embedder",
                                       n_labels=cfg.n_labels)
        head_model = EmbedderClassifier(ecfg)
        ids = jnp.zeros((1, 8), jnp.int32)
        mask = jnp.ones((1, 8), jnp.bool_)
        init = head_model.init(jax.random.PRNGKey(cfg.seed), ids, mask)
        loaded = {"params": {**loaded["params"],
                             "cls_head": init["params"]["cls_head"]}}
    if params is None:
        params = loaded
    if tokenizer is None:
        from .tokenizer import from_pretrained_dir

        try:
            tokenizer = from_pretrained_dir(path)
        except Exception as e:
            # Falling back to HashingTokenizer silently would serve real
            # weights over garbage token ids — make the downgrade visible.
            logging.getLogger(__name__).warning(
                "no usable tokenizer in %s (%s); falling back to "
                "HashingTokenizer", path, e)
            tokenizer = None
    return ecfg, params, tokenizer


def _migrate_split_qkv(params):
    """Fuse legacy per-projection attention params on checkpoint load.

    Checkpoints written before the fused-QKV encoder carry separate
    ``attn/{q,k,v}`` trees; the model now expects one ``qkv/kernel``
    [h, 3, h] + ``qkv/bias`` [3, h].  Stacking on load keeps the
    'a deployment resumes exactly' guarantee across the layout change."""
    enc = params.get("params", {}).get("encoder")
    if not isinstance(enc, dict):
        return params
    for name, layer in enc.items():
        if not name.startswith("layers_") or "attn" not in layer:
            continue
        attn = layer["attn"]
        if "qkv/kernel" in attn or "q" not in attn:
            continue
        q, k, v = attn.pop("q"), attn.pop("k"), attn.pop("v")
        attn["qkv/kernel"] = np.stack(
            [np.asarray(q["kernel"]), np.asarray(k["kernel"]),
             np.asarray(v["kernel"])], axis=1)
        attn["qkv/bias"] = np.stack(
            [np.asarray(q["bias"]), np.asarray(k["bias"]),
             np.asarray(v["bias"])], axis=0)
    return params


def _softmax_np(logits: np.ndarray) -> np.ndarray:
    z = logits - logits.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)
