"""ASR pipeline: media files -> Whisper transcripts (BASELINE config #4).

The reference crawls Telegram voice/video media to local files
(`telegramhelper/tdutils.go:226-358`); this stage transcribes them with the
Whisper family.  Host side: WAV decode (PCM16, stdlib `wave`; non-16 kHz
rates are box-filtered + linearly resampled in-process — see
`read_wav_mono_16k` — while codec handling, OGG/Opus/video, stays an
upstream ffmpeg concern), fixed 30 s windows; device side: one jitted
`transcribe_features` call per batch, padded to a static batch size so
there is exactly one compiled program.

Transcripts come back as token-id arrays; `detokenize` is a pluggable hook
(a sentencepiece/BPE vocab is deployment data, not framework code — wire the
real Whisper vocab in production, identity-join in tests).
"""

from __future__ import annotations

import logging
import wave
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

logger = logging.getLogger("dct.inference.asr")


def read_wav_mono_16k(path: str) -> np.ndarray:
    """PCM16 WAV -> float32 mono waveform in [-1, 1] at 16 kHz.

    Other sample rates are resampled in-process so a stray 48 kHz export
    doesn't fail a whole transcription run: a box low-pass sized to the
    decimation ratio first (knocks down energy above the new Nyquist that
    would otherwise alias INTO the speech band), then linear
    interpolation.  Good enough for speech ASR; bit-exact resampling and
    codec handling (OGG/Opus voice notes, video audio) belong to an
    upstream ffmpeg step."""
    with wave.open(path, "rb") as w:
        rate = w.getframerate()
        n = w.getnframes()
        raw = w.readframes(n)
        audio = np.frombuffer(raw, dtype=np.int16).astype(np.float32)
        channels = w.getnchannels()
    if channels > 1:
        audio = audio.reshape(-1, channels).mean(axis=1)
    audio = audio / 32768.0
    if rate != 16_000 and len(audio):
        if rate <= 0:
            raise ValueError(f"{path}: invalid sample rate {rate}")
        if rate > 16_000:
            k = int(round(rate / 16_000))
            if k > 1:  # anti-alias before downsampling
                audio = np.convolve(audio, np.ones(k, np.float32) / k,
                                    mode="same")
        n_out = max(1, int(round(len(audio) * 16_000 / rate)))
        audio = np.interp(
            np.linspace(0.0, len(audio) - 1.0, n_out),
            np.arange(len(audio), dtype=np.float64),
            audio).astype(np.float32)
        logger.debug("resampled %s: %d Hz -> 16 kHz (%d samples)",
                     path, rate, n_out)
    return audio


@dataclass
class ASRResult:
    path: str
    tokens: List[int] = field(default_factory=list)
    text: str = ""


class ASRPipeline:
    """Batch transcriber over a Whisper model."""

    @classmethod
    def from_pretrained(cls, path: str, batch_size: int = 8,
                        max_len: Optional[int] = None,
                        dtype: str = "bfloat16") -> "ASRPipeline":
        """Build from a local HF Whisper checkpoint dir: real weights via
        `models.hf_convert.load_hf_whisper`, real vocab via tokenizer.json
        when present (detokenize wired automatically)."""
        from dataclasses import replace as dc_replace

        from ..models.hf_convert import load_hf_whisper
        from ..models.whisper import Whisper

        cfg, params = load_hf_whisper(path)
        cfg = dc_replace(cfg, dtype=dtype)
        detok = None
        try:
            from .tokenizer import from_pretrained_dir

            tok = from_pretrained_dir(path)
            rust = getattr(tok, "decode", None)
            if rust is not None:
                detok = lambda ids: tok.decode(list(ids))  # noqa: E731
        except Exception:
            logger.info("no tokenizer assets in %s; token-id output only",
                        path)
        return cls(Whisper(cfg), params, batch_size=batch_size,
                   max_len=max_len, detokenize=detok)

    def __init__(self, model, params, batch_size: int = 8,
                 max_len: Optional[int] = None,
                 detokenize: Optional[Callable[[Sequence[int]], str]] = None):
        import jax

        from ..models.whisper import transcribe_features

        self.model = model
        self.params = params
        self.batch_size = batch_size
        self.max_len = max_len or model.cfg.n_text_ctx
        self.detokenize = detokenize
        self._transcribe = jax.jit(
            lambda p, audio: transcribe_features(model, p, audio,
                                                 max_len=self.max_len))

    def strip_special(self, tokens: Sequence[int]) -> List[int]:
        cfg = self.model.cfg
        special = {cfg.sot_token, cfg.eot_token, cfg.no_timestamps_token,
                   cfg.transcribe_token}
        return [int(t) for t in tokens if int(t) not in special]

    def transcribe_audio(self, audio_batch: np.ndarray) -> np.ndarray:
        """waveforms [B, T] -> token ids [B, L] (single device dispatch)."""
        import jax.numpy as jnp
        return np.asarray(self._transcribe(self.params,
                                           jnp.asarray(audio_batch)))

    def transcribe_files(self, paths: Sequence[str]) -> List[ASRResult]:
        """Pad the final partial batch to the static batch size so every
        dispatch reuses one compiled program."""
        from ..models.whisper import audio_window_samples

        window = audio_window_samples(self.model.cfg)
        results: List[ASRResult] = []
        for start in range(0, len(paths), self.batch_size):
            chunk = list(paths[start:start + self.batch_size])
            audios = []
            kept = []
            for p in chunk:
                try:
                    audios.append(read_wav_mono_16k(p))
                    kept.append(p)
                except Exception as e:
                    logger.error("failed to read %s: %s", p, e)
                    results.append(ASRResult(path=p, tokens=[], text=""))
            if not kept:
                continue
            batch = np.zeros((self.batch_size, window), np.float32)
            for i, a in enumerate(audios):
                batch[i, :min(len(a), window)] = a[:window]
            tokens = self.transcribe_audio(batch)
            for i, p in enumerate(kept):
                toks = self.strip_special(tokens[i])
                text = self.detokenize(toks) if self.detokenize else ""
                results.append(ASRResult(path=p, tokens=toks, text=text))
        return results
