"""ASR pipeline: media files -> Whisper transcripts (BASELINE config #4).

The reference crawls Telegram voice/video media to local files
(`telegramhelper/tdutils.go:226-358`); this stage transcribes them with the
Whisper family.  Host side: WAV decode (PCM16, stdlib `wave`; non-16 kHz
rates are box-filtered + linearly resampled in-process — see
`read_wav_mono_16k` — while codec handling, OGG/Opus/video, stays an
upstream ffmpeg concern), then `media/chunker.py` slices every file into
fixed 30 s windows and buckets them by window count; device side: one
jitted `transcribe_features` program PER WINDOW-COUNT BUCKET (jit
re-traces per batch shape, so the bucket set IS the program set — the
PR-1 bucketing discipline on the batch axis).  Long files are windowed,
transcribed window by window, and reassembled in order — never truncated
to the first 30 s.

Both the offline `mode=transcribe` path and the serving `ASRWorker`
(`media/worker.py`) run through :meth:`ASRPipeline.transcribe_plan`, so
batch and offline share ONE featurize path.

Cost/efficiency accounting mirrors `inference/engine.py`: each bucket
program's compiled cost is captured at first dispatch
(`utils/costmodel.CostModel`, analytic `whisper_forward_flops` fallback)
and every dispatch feeds the rolling MFU/goodput meter, so `/costs`
shows honest Whisper rows next to the text programs.

Transcripts come back as token-id arrays; `detokenize` is a pluggable hook
(a sentencepiece/BPE vocab is deployment data, not framework code — wire the
real Whisper vocab in production, identity-join in tests).
"""

from __future__ import annotations

import logging
import threading
import time
import wave
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils import trace
from ..utils.costmodel import CostModel, EfficiencyMeter, whisper_forward_flops
from ..utils.metrics import REGISTRY, MetricsRegistry
from ..utils.occupancy import DeviceTimeline

logger = logging.getLogger("dct.inference.asr")


def read_wav_mono_16k(path: str) -> np.ndarray:
    """PCM16 WAV -> float32 mono waveform in [-1, 1] at 16 kHz.

    Other sample rates are resampled in-process so a stray 48 kHz export
    doesn't fail a whole transcription run: a box low-pass sized to the
    decimation ratio first (knocks down energy above the new Nyquist that
    would otherwise alias INTO the speech band), then linear
    interpolation.  Good enough for speech ASR; bit-exact resampling and
    codec handling (OGG/Opus voice notes, video audio) belong to an
    upstream ffmpeg step."""
    with wave.open(path, "rb") as w:
        rate = w.getframerate()
        n = w.getnframes()
        raw = w.readframes(n)
        audio = np.frombuffer(raw, dtype=np.int16).astype(np.float32)
        channels = w.getnchannels()
    if channels > 1:
        audio = audio.reshape(-1, channels).mean(axis=1)
    audio = audio / 32768.0
    if rate != 16_000 and len(audio):
        if rate <= 0:
            raise ValueError(f"{path}: invalid sample rate {rate}")
        if rate > 16_000:
            k = int(round(rate / 16_000))
            if k > 1:  # anti-alias before downsampling
                audio = np.convolve(audio, np.ones(k, np.float32) / k,
                                    mode="same")
        n_out = max(1, int(round(len(audio) * 16_000 / rate)))
        audio = np.interp(
            np.linspace(0.0, len(audio) - 1.0, n_out),
            np.arange(len(audio), dtype=np.float64),
            audio).astype(np.float32)
        logger.debug("resampled %s: %d Hz -> 16 kHz (%d samples)",
                     path, rate, n_out)
    return audio


@dataclass
class ASRResult:
    path: str
    tokens: List[int] = field(default_factory=list)
    text: str = ""
    windows: int = 0     # 30 s windows transcribed (0 on failure)
    error: str = ""      # non-empty = the file failed to decode


def default_window_buckets(batch_size: int) -> tuple:
    """Powers of two up to ``batch_size`` (plus batch_size itself): the
    window-count buckets one ASR deployment compiles."""
    out = []
    b = 1
    while b < batch_size:
        out.append(b)
        b *= 2
    out.append(max(1, int(batch_size)))
    return tuple(sorted(set(out)))


class ASRPipeline:
    """Bucketed batch transcriber over a Whisper model."""

    @classmethod
    def from_pretrained(cls, path: str, batch_size: int = 8,
                        max_len: Optional[int] = None,
                        dtype: str = "bfloat16",
                        window_buckets: Optional[Sequence[int]] = None,
                        registry: MetricsRegistry = REGISTRY
                        ) -> "ASRPipeline":
        """Build from a local HF Whisper checkpoint dir: real weights via
        `models.hf_convert.load_hf_whisper`, real vocab via tokenizer.json
        when present (detokenize wired automatically)."""
        from dataclasses import replace as dc_replace

        from ..models.hf_convert import load_hf_whisper
        from ..models.whisper import Whisper

        cfg, params = load_hf_whisper(path)
        cfg = dc_replace(cfg, dtype=dtype)
        detok = None
        try:
            from .tokenizer import from_pretrained_dir

            tok = from_pretrained_dir(path)
            rust = getattr(tok, "decode", None)
            if rust is not None:
                detok = lambda ids: tok.decode(list(ids))  # noqa: E731
        except Exception:
            logger.info("no tokenizer assets in %s; token-id output only",
                        path)
        return cls(Whisper(cfg), params, batch_size=batch_size,
                   max_len=max_len, detokenize=detok,
                   window_buckets=window_buckets, registry=registry)

    def __init__(self, model, params, batch_size: int = 8,
                 max_len: Optional[int] = None,
                 detokenize: Optional[Callable[[Sequence[int]], str]] = None,
                 window_buckets: Optional[Sequence[int]] = None,
                 registry: MetricsRegistry = REGISTRY):
        import jax

        from ..media.chunker import AudioChunker
        from ..models.whisper import (
            SAMPLE_RATE,
            audio_window_samples,
            transcribe_features,
        )

        self.model = model
        self.params = params
        self.batch_size = batch_size
        self.max_len = max_len or model.cfg.n_text_ctx
        self.detokenize = detokenize
        self.sample_rate = SAMPLE_RATE
        self.window_samples = audio_window_samples(model.cfg)
        self.window_buckets = tuple(window_buckets) if window_buckets \
            else default_window_buckets(batch_size)
        self.chunker = AudioChunker(self.window_samples,
                                    buckets=self.window_buckets)
        # jit re-traces per input shape, so each window-count bucket gets
        # its own compiled program through this ONE jitted callable.
        self._transcribe = jax.jit(
            lambda p, audio: transcribe_features(model, p, audio,
                                                 max_len=self.max_len))
        # Cost/efficiency accounting (shared metric families with the
        # text engine; ASR rows are distinguished by path="asr" labels).
        self.costs = CostModel(registry=registry)
        self.meter = EfficiencyMeter(registry=registry)
        # Device-occupancy accounting (`utils/occupancy.py`): the ASR
        # dispatch is synchronous (tokens materialize in the same call),
        # so overlap stays 0 by construction — the busy-fraction and
        # bubble numbers are what say whether the decode loop kept the
        # chip fed between bucketed batches.  The ASR worker's feed loop
        # marks queue-empty via start_stream(), same as the text worker.
        self.timeline = DeviceTimeline(registry=registry, path="asr")
        self.m_windows = registry.counter(
            "asr_windows_total", "30 s audio windows through Whisper")
        self.m_pad_windows = registry.counter(
            "asr_pad_window_slots_total",
            "wasted window slots (bucket padding)")
        self.m_compile_miss = registry.counter(
            "tpu_engine_compile_cache_misses_total",
            "jit program builds by bucket and path (first-dispatch "
            "compiles)")
        self._lock = threading.Lock()
        self._seen_buckets: set = set()

    def strip_special(self, tokens: Sequence[int]) -> List[int]:
        cfg = self.model.cfg
        special = {cfg.sot_token, cfg.eot_token, cfg.no_timestamps_token,
                   cfg.transcribe_token}
        return [int(t) for t in tokens if int(t) not in special]

    # -- device dispatch -----------------------------------------------------
    def transcribe_audio(self, audio_batch: np.ndarray,
                         real_windows: Optional[int] = None,
                         record: bool = True) -> np.ndarray:
        """waveforms [B, T] -> token ids [B, L] (single device dispatch).

        ``B`` should be one of ``window_buckets`` (each distinct B is a
        compiled program).  ``real_windows`` (default B) drives the
        efficiency meter's real-vs-slot accounting; ``record=False``
        (warmup) captures program cost but keeps the compile-dominated
        dispatch OUT of the MFU/goodput window and padding counters.
        """
        import jax.numpy as jnp

        bucket = int(audio_batch.shape[0])
        real = bucket if real_windows is None else int(real_windows)
        with self._lock:
            first = bucket not in self._seen_buckets
            self._seen_buckets.add(bucket)
        if first:
            self.m_compile_miss.labels(bucket=str(bucket),
                                       path="asr").inc()
        placed = jnp.asarray(audio_batch)
        t0 = time.perf_counter()
        with trace.span("asr.transcribe", bucket=bucket, windows=real):
            tokens = np.asarray(self._transcribe(self.params, placed))
        dt = time.perf_counter() - t0
        if record:  # warmup compiles must not score as busy time
            self.timeline.record(t0, t0 + dt)
        self._account(bucket, placed, dt, real, record)
        return tokens

    def _account(self, bucket: int, placed, dt: float, real: int,
                 record: bool) -> None:
        """Cost capture (first dispatch per bucket) + meter feed; never
        raises into the transcription path (`CostModel` contract)."""
        cfg = self.model.cfg
        analytic = whisper_forward_flops(cfg, bucket, self.max_len)
        if not self.costs.has(bucket, "asr"):
            self.costs.capture(
                bucket, "asr",
                lambda: self._transcribe.lower(self.params, placed),
                analytic, batch=bucket, seq=cfg.n_audio_ctx)
        if not record:
            return  # warmup: cost captured, no phantom efficiency samples
        # Goodput unit: encoder positions (the audio-side "tokens") —
        # real windows vs dispatched slot windows.
        self.meter.record(dt, self.costs.flops_for(bucket, "asr", analytic),
                          real * cfg.n_audio_ctx,
                          bucket * cfg.n_audio_ctx)
        self.m_windows.inc(real)
        self.m_pad_windows.inc(bucket - real)

    def transcribe_plan(self, plan) -> List[List[int]]:
        """A `media.chunker.ChunkPlan` -> special-stripped token lists,
        one per plan window (the ONE featurize path batch and offline
        share).  Dispatches one bucketed program per `WindowBatch`."""
        per_window: List[List[int]] = [[] for _ in range(plan.n_windows)]
        for wb in self.chunker.batches(plan):
            tokens = self.transcribe_audio(wb.audio,
                                           real_windows=wb.real_windows)
            for row, w in enumerate(wb.window_indices):
                per_window[w] = self.strip_special(tokens[row])
        return per_window

    # -- file front door -----------------------------------------------------
    def transcribe_files(self, paths: Sequence[str]) -> List[ASRResult]:
        """Decode, window, transcribe, reassemble — results in INPUT
        order, failures explicit (``error`` set, empty tokens).  Long
        files are windowed across as many 30 s windows as they span and
        reassembled, never truncated to the first window."""
        plan = self.chunker.chunk_files(paths)
        per_window = self.transcribe_plan(plan)
        per_file = self.chunker.reassemble(plan, per_window)
        counts = plan.windows_per_file()
        results: List[ASRResult] = []
        for i, p in enumerate(paths):
            if i in plan.errors:
                results.append(ASRResult(path=p, error=plan.errors[i]))
                continue
            toks = per_file[i]
            text = self.detokenize(toks) if self.detokenize else ""
            results.append(ASRResult(path=p, tokens=toks, text=text,
                                     windows=counts[i]))
        return results

    # -- serving support (`media/worker.py`) ---------------------------------
    def warmup(self, buckets: Optional[Sequence[int]] = None) -> None:
        """Pre-compile every window-count bucket's program before serving
        (first decode of the largest bucket is the longest on-chip
        window; live batches must not pay it)."""
        for b in buckets or self.window_buckets:
            audio = np.zeros((int(b), self.window_samples), np.float32)
            self.transcribe_audio(audio, real_windows=0, record=False)

    def compile_cache_stats(self) -> Dict[str, Any]:
        """Telemetry-heartbeat shape shared with
        `InferenceEngine.compile_cache_stats` (the emitter computes
        per-beat miss deltas from ``misses_total``)."""
        misses: Dict[str, float] = {}
        total = 0.0
        for labels, value in self.m_compile_miss.series():
            if not labels or labels.get("path") != "asr":
                continue
            misses[f"asr:{labels.get('bucket', '?')}"] = value
            total += value
        with self._lock:
            programs = sorted(self._seen_buckets)
        return {"programs_asr": programs, "misses_total": total,
                "misses": misses}

    def efficiency_snapshot(self) -> Dict[str, Any]:
        return self.meter.snapshot()

    def occupancy_snapshot(self) -> Dict[str, Any]:
        """Telemetry-heartbeat twin of the engine's; also refreshes the
        path="asr" busy/overlap gauges."""
        return self.timeline.snapshot()

    def cost_snapshot(self) -> Dict[str, Any]:
        """The ASR worker's /costs body core: Whisper program rows +
        the rolling efficiency window."""
        return {
            "model": "whisper",
            "batch_size": self.batch_size,
            "window_buckets": list(self.window_buckets),
            "window_samples": self.window_samples,
            "decode_len": self.max_len,
            "costs": self.costs.snapshot(),
            "efficiency": self.meter.snapshot(),
            "occupancy": self.timeline.snapshot(),
        }
