"""Platform crawler registry (reference `crawler/` + `crawler/common/`).

`CrawlerFactory` + `register_all_crawlers` mirror the reference's
`DefaultCrawlerFactory` (`crawler/crawler.go:79-106`) and
`RegisterAllCrawlers` (`crawler/common/registrar.go:11-25`).
"""

from .base import (
    PLATFORM_TELEGRAM,
    PLATFORM_YOUTUBE,
    Crawler,
    CrawlerFactory,
    CrawlJob,
    CrawlResult,
    CrawlRunner,
    CrawlTarget,
)
from .telegram import TelegramCrawler, register_telegram_crawler
from .youtube import (
    YouTubeCrawler,
    apply_sampling,
    extract_urls,
    parse_iso8601_duration,
    register_youtube_crawler,
    sanitize_filename,
)


def register_all_crawlers(factory: CrawlerFactory) -> None:
    """`crawler/common/registrar.go:11-25`."""
    register_telegram_crawler(factory)
    register_youtube_crawler(factory)


__all__ = [
    "PLATFORM_TELEGRAM",
    "PLATFORM_YOUTUBE",
    "Crawler",
    "CrawlerFactory",
    "CrawlJob",
    "CrawlResult",
    "CrawlRunner",
    "CrawlTarget",
    "TelegramCrawler",
    "YouTubeCrawler",
    "apply_sampling",
    "extract_urls",
    "parse_iso8601_duration",
    "register_all_crawlers",
    "register_telegram_crawler",
    "register_youtube_crawler",
    "sanitize_filename",
]
