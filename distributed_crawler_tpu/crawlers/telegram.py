"""Telegram crawler: the `Crawler` interface over the native client boundary.

Parity with the reference's `crawler/telegram/telegram_crawler.go` (~330 LoC):
initialize from a config map holding the client + state manager (`:31-62`),
target validation (`:65-76`), channel info via the client (`:78-116`), and
message fetching that delegates to the engine's fetch + parse pipeline
(`:118-161`).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict

from ..clients.telegram import TelegramClient
from ..config.crawler import CrawlerConfig
from ..crawl.channelinfo import get_channel_info as engine_channel_info
from ..datamodel import ChannelData, EngagementData
from ..state.datamodels import Page, new_id
from ..telegram.parsing import parse_message
from .base import (
    PLATFORM_TELEGRAM,
    Crawler,
    CrawlerFactory,
    CrawlJob,
    CrawlResult,
    CrawlTarget,
)

logger = logging.getLogger("dct.crawlers.telegram")


class TelegramCrawler(Crawler):
    """`crawler.Crawler` impl delegating to the Telegram client boundary
    (`crawler/telegram/telegram_crawler.go:17-28`)."""

    def __init__(self):
        self.client: TelegramClient = None  # type: ignore[assignment]
        self.sm = None
        self.cfg: CrawlerConfig = CrawlerConfig()
        self.initialized = False

    def initialize(self, config: Dict[str, Any]) -> None:
        """`telegram_crawler.go:31-62`."""
        if self.initialized:
            return
        client = config.get("client")
        if client is None:
            raise ValueError("client not provided in config")
        self.client = client
        self.sm = config.get("state_manager")
        cfg = config.get("crawler_config")
        if cfg is not None:
            self.cfg = cfg
        self.initialized = True

    def validate_target(self, target: CrawlTarget) -> None:
        """`telegram_crawler.go:65-76`."""
        if target.type != PLATFORM_TELEGRAM:
            raise ValueError(
                f"invalid target type: {target.type}, expected: telegram")
        if not target.id:
            raise ValueError("target ID cannot be empty")

    def get_platform_type(self) -> str:
        return PLATFORM_TELEGRAM

    def close(self) -> None:
        if self.client is not None:
            self.client.close()

    def get_channel_info(self, target: CrawlTarget) -> ChannelData:
        """`telegram_crawler.go:78-116`."""
        self.validate_target(target)
        if not self.initialized:
            raise RuntimeError("crawler not initialized")
        page = Page(id=new_id(), url=target.id)
        info, _ = engine_channel_info(self.client, page, 0, self.cfg)
        return ChannelData(
            channel_id=str(info.chat.id),
            channel_name=info.chat.title,
            channel_description=(info.supergroup_info.description
                                 if info.supergroup_info else ""),
            channel_engagement_data=EngagementData(
                follower_count=info.member_count,
                post_count=info.message_count,
                views_count=info.total_views,
            ),
            channel_url=f"https://t.me/{target.id}",
            channel_url_external=f"https://t.me/{target.id}",
        )

    def fetch_messages(self, job: CrawlJob) -> CrawlResult:
        """Fetch + parse into Posts (`telegram_crawler.go:118-161`).

        The job window/limit/sample are layered onto the crawler config so
        the channel history is paged exactly once."""
        self.validate_target(job.target)
        if not self.initialized:
            raise RuntimeError("crawler not initialized")

        cfg = dataclasses.replace(self.cfg)
        if job.from_time is not None:
            cfg.min_post_date = job.from_time
        if job.to_time is not None:
            cfg.date_between_max = job.to_time
        if job.limit:
            cfg.max_posts = job.limit
        if job.sample_size:
            cfg.sample_size = job.sample_size

        page = Page(id=new_id(), url=job.target.id)
        info, messages = engine_channel_info(self.client, page, 0, cfg)

        posts = []
        errors = []
        for m in messages:
            try:
                post = parse_message(
                    self.cfg.crawl_id, m, info.chat_details, info.supergroup,
                    info.supergroup_info, info.message_count, info.total_views,
                    job.target.id, self.client, self.sm, self.cfg)
            except Exception as e:
                logger.error("failed to convert message to post", extra={
                    "message_id": m.id, "error": str(e)})
                errors.append(str(e))
                continue
            if job.null_validator is not None:
                result = job.null_validator.validate_post(post)
                if not result.valid:
                    logger.error("missing critical fields in telegram post",
                                 extra={"errors": result.errors})
            posts.append(post)
        return CrawlResult(posts=posts, errors=errors)


def register_telegram_crawler(factory: CrawlerFactory) -> None:
    """`crawler/telegram/registers.go:8`."""
    factory.register_crawler(PLATFORM_TELEGRAM, TelegramCrawler)
