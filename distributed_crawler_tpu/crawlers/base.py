"""Platform-agnostic crawler abstraction: interface, registry, runner.

Parity with the reference's `crawler/crawler.go:15-106` (PlatformType,
CrawlTarget/CrawlJob/CrawlResult, the `Crawler` interface, and the
registry-based `DefaultCrawlerFactory`) and `crawler/common/runner.go:15-156`
(the generic `CrawlRunner` that validates, fetches, and stores).
"""

from __future__ import annotations

import abc
import logging
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Callable, Dict, List, Optional

from ..datamodel import ChannelData, NullValidator, Post

logger = logging.getLogger("dct.crawlers")

PLATFORM_TELEGRAM = "telegram"
PLATFORM_YOUTUBE = "youtube"


@dataclass
class CrawlTarget:
    """A specific source to crawl (`crawler/crawler.go:25-29`)."""

    id: str = ""
    type: str = PLATFORM_TELEGRAM
    metadata: Dict[str, str] = field(default_factory=dict)


@dataclass
class CrawlResult:
    """Unified crawl results (`crawler/crawler.go:32-35`)."""

    posts: List[Post] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)


@dataclass
class CrawlJob:
    """A job to crawl one target (`crawler/crawler.go:38-46`)."""

    target: CrawlTarget = field(default_factory=CrawlTarget)
    from_time: Optional[datetime] = None
    to_time: Optional[datetime] = None
    limit: int = 0
    sample_size: int = 0  # 0 = no post-level sampling
    samples_remaining: int = 0
    null_validator: Optional[NullValidator] = None


class Crawler(abc.ABC):
    """The interface every platform crawler implements
    (`crawler/crawler.go:49-67`)."""

    @abc.abstractmethod
    def initialize(self, config: Dict[str, Any]) -> None:
        """Set up the crawler with necessary configuration."""

    @abc.abstractmethod
    def validate_target(self, target: CrawlTarget) -> None:
        """Raise ValueError if the target is not valid for this crawler."""

    @abc.abstractmethod
    def get_channel_info(self, target: CrawlTarget) -> ChannelData:
        """Retrieve information about a channel."""

    @abc.abstractmethod
    def fetch_messages(self, job: CrawlJob) -> CrawlResult:
        """Retrieve messages/posts from the target."""

    @abc.abstractmethod
    def get_platform_type(self) -> str: ...

    @abc.abstractmethod
    def close(self) -> None: ...


class CrawlerFactory:
    """Registry-based factory (`crawler/crawler.go:70-106`)."""

    def __init__(self):
        self._creators: Dict[str, Callable[[], Crawler]] = {}

    def register_crawler(self, platform_type: str,
                         creator: Callable[[], Crawler]) -> None:
        if platform_type in self._creators:
            raise ValueError(
                f"crawler for platform {platform_type} already registered")
        self._creators[platform_type] = creator

    def get_crawler(self, platform_type: str) -> Crawler:
        creator = self._creators.get(platform_type)
        if creator is None:
            raise ValueError(
                f"no crawler registered for platform {platform_type}")
        return creator()

    def registered_platforms(self) -> List[str]:
        return sorted(self._creators)


class CrawlRunner:
    """Generic job runner: get-or-init crawler, validate, fetch, store
    (`crawler/common/runner.go:15-156`)."""

    def __init__(self, factory: CrawlerFactory, state_manager,
                 base_config: Optional[Dict[str, Any]] = None):
        self.factory = factory
        self.sm = state_manager
        self.base_config = dict(base_config or {})
        self._crawlers: Dict[str, Crawler] = {}

    def _get_crawler(self, platform_type: str) -> Crawler:
        c = self._crawlers.get(platform_type)
        if c is not None:
            return c
        c = self.factory.get_crawler(platform_type)
        config = {"state_manager": self.sm, **self.base_config}
        c.initialize(config)
        self._crawlers[platform_type] = c
        return c

    def execute_job(self, job: CrawlJob) -> CrawlResult:
        c = self._get_crawler(job.target.type)
        c.validate_target(job.target)
        result = c.fetch_messages(job)
        # The YouTube crawler stores as it converts; store here only for
        # crawlers that don't (store_post must be idempotent either way —
        # parity `runner.go:54-63` which always re-saves).
        for post in result.posts:
            if not getattr(c, "stores_posts_itself", False):
                try:
                    self.sm.store_post(post.channel_id, post)
                except Exception as e:
                    logger.error("failed to save post", extra={
                        "post_uid": post.post_uid, "error": str(e)})
        return result

    def execute_batch_jobs(self, jobs: List[CrawlJob]) -> List[CrawlResult]:
        results: List[CrawlResult] = []
        for job in jobs:
            try:
                results.append(self.execute_job(job))
            except Exception as e:
                logger.error("job failed", extra={
                    "platform": job.target.type, "target_id": job.target.id,
                    "error": str(e)})
                results.append(CrawlResult(posts=[], errors=[str(e)]))
        return results

    def get_channel_info(self, target: CrawlTarget) -> ChannelData:
        return self._get_crawler(target.type).get_channel_info(target)

    def close(self) -> None:
        for platform, c in self._crawlers.items():
            try:
                c.close()
            except Exception as e:
                logger.error("error closing crawler", extra={
                    "platform": platform, "error": str(e)})
        self._crawlers.clear()
