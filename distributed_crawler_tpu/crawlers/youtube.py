"""YouTube crawler: channel / random / snowball sampling over the Data API.

Parity with the reference's `crawler/youtube/youtube_crawler.go` (871 LoC):
- Initialize from a config map (client, state manager, sampling method, seed
  channels, min-channel-videos; `:79-177`)
- 3-way sampling switch in `fetch_messages` (`:287-351`)
- parallel video->Post conversion pool (10 workers, `:353-427`)
- ISO-8601 duration parsing (`:461-487`)
- URL extraction + filename sanitization (`:489-527`)
- the 75-field video->Post mapping (`:530-838`)
- post-level Fisher-Yates sampling (`:839-871`)
"""

from __future__ import annotations

import dataclasses
import logging
import random
import re
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ..datamodel import ChannelData, EngagementData, Post
from ..datamodel.post import MediaData, OCRData, PerformanceScores
from ..datamodel.youtube import YouTubeChannel, YouTubeVideo
from ..state.datamodels import utcnow
from .base import (
    PLATFORM_YOUTUBE,
    Crawler,
    CrawlerFactory,
    CrawlJob,
    CrawlResult,
    CrawlTarget,
)

logger = logging.getLogger("dct.crawlers.youtube")

SAMPLING_CHANNEL = "channel"
SAMPLING_RANDOM = "random"
SAMPLING_SNOWBALL = "snowball"

MAX_POST_WORKERS = 10  # `youtube_crawler.go:355`

_ISO8601_DURATION = re.compile(
    r"^P(?:(?P<days>\d+)D)?"
    r"(?:T(?:(?P<hours>\d+)H)?(?:(?P<minutes>\d+)M)?(?:(?P<seconds>\d+)S)?)?$")

_URL_PATTERN = re.compile(r"https?://[^\s<>\"]+")

_FILENAME_SANITIZER = re.compile(r"[^\w\-.]")


def parse_iso8601_duration(duration: str) -> int:
    """Duration string -> total seconds (`youtube_crawler.go:461-487`)."""
    m = _ISO8601_DURATION.match(duration)
    if m is None or (m.group("days") is None and m.group("hours") is None
                     and m.group("minutes") is None
                     and m.group("seconds") is None):
        raise ValueError(f"invalid ISO 8601 duration: {duration}")
    parts = {k: int(v) if v else 0 for k, v in m.groupdict().items()}
    return (parts["days"] * 86400 + parts["hours"] * 3600
            + parts["minutes"] * 60 + parts["seconds"])


def extract_urls(text: str) -> List[str]:
    """Deduped URLs with trailing punctuation trimmed
    (`youtube_crawler.go:489-513`)."""
    seen: Dict[str, bool] = {}
    for url in _URL_PATTERN.findall(text or ""):
        seen[url.rstrip(",.;:!?()'\"")] = True
    return list(seen)


def sanitize_filename(filename: str) -> str:
    """Non-word chars -> underscore, 50-char cap (`youtube_crawler.go:516-527`)."""
    return _FILENAME_SANITIZER.sub("_", filename)[:50]


def apply_sampling(posts: List[Post], sample_size: int,
                   rng: Optional[random.Random] = None) -> List[Post]:
    """Fisher-Yates shuffle, keep the first `sample_size`
    (`youtube_crawler.go:839-871`)."""
    if sample_size <= 0 or len(posts) <= sample_size:
        return posts
    rng = rng or random.Random()
    shuffled = list(posts)
    rng.shuffle(shuffled)
    return shuffled[:sample_size]


def _channel_url(channel_id: str) -> str:
    """`youtube_crawler.go:209-214`: @username vs UC... id formats."""
    if channel_id.startswith("@"):
        return f"https://www.youtube.com/{channel_id}"
    return f"https://www.youtube.com/channel/{channel_id}"


def youtube_channel_id(target: str) -> str:
    """Extract the channel identifier from a seed URL or pass a bare id
    through unchanged, preserving case (UC... ids are case-sensitive, so
    the telegram-style lowercasing in `normalize_seed_urls` must never
    touch YouTube seeds).

    Accepted shapes: ``https://(www.)youtube.com/channel/UC...[/tab]``,
    ``.../@handle[/tab]``, ``.../user/Name`` (legacy ``forUsername``,
    returned as ``user/Name``), bare ``UC...``, bare ``@handle``.
    ``/c/CustomName`` URLs are rejected: the Data API has no lookup for
    custom URLs — re-seed with the UC id or @handle."""
    rest = target.strip()
    for prefix in ("https://www.youtube.com/", "http://www.youtube.com/",
                   "https://youtube.com/", "http://youtube.com/",
                   "www.youtube.com/", "youtube.com/"):
        if rest.startswith(prefix):
            rest = rest[len(prefix):]
            break
    else:
        return rest  # bare id / handle
    rest = rest.split("?", 1)[0].strip("/")
    if rest.startswith("c/"):
        raise ValueError(
            f"custom URL {target!r} cannot be resolved through the Data "
            f"API; seed with the channel's UC id or @handle instead")
    if rest.startswith("channel/"):
        rest = rest[len("channel/"):]
        return rest.split("/", 1)[0]  # drop trailing /videos etc.
    if rest.startswith("user/"):
        return "user/" + rest[len("user/"):].split("/", 1)[0]
    return rest.split("/", 1)[0]  # "@handle[/tab]" or naked segment


def _best_thumbnail(thumbnails: Dict[str, str]) -> str:
    for quality in ("maxres", "high", "medium", "default"):
        url = thumbnails.get(quality, "")
        if url:
            return url
    return ""


class YouTubeCrawler(Crawler):
    """`crawler.Crawler` implementation for YouTube
    (`crawler/youtube/youtube_crawler.go:40-62`)."""

    stores_posts_itself = True  # conversion workers call store_post directly

    def __init__(self):
        self.client = None
        self.sm = None
        self.sampling_method = SAMPLING_CHANNEL
        self.seed_channels: List[str] = []
        self.min_channel_videos = 0
        self.crawl_label = ""
        self.initialized = False

    # -- lifecycle ---------------------------------------------------------
    def initialize(self, config: Dict[str, Any]) -> None:
        """`youtube_crawler.go:79-177`; requires a connected client in
        config["client"] (the runner injects it) and a state manager."""
        self.client = config.get("client")
        if self.client is None:
            raise ValueError("youtube crawler requires a 'client' in config")
        self.sm = config.get("state_manager")
        self.sampling_method = config.get("sampling_method",
                                          SAMPLING_CHANNEL) or SAMPLING_CHANNEL
        self.seed_channels = list(config.get("seed_channels") or [])
        mv = config.get("min_channel_videos")
        self.min_channel_videos = int(mv) if mv is not None else 0
        self.crawl_label = config.get("crawl_label", "") or ""
        self.initialized = True

    def validate_target(self, target: CrawlTarget) -> None:
        """`youtube_crawler.go:179-190`."""
        if target.type != PLATFORM_YOUTUBE:
            raise ValueError(
                f"invalid target type for YouTube crawler: {target.type}")
        if not target.id and self.sampling_method == SAMPLING_CHANNEL:
            raise ValueError("target ID cannot be empty for channel sampling")

    def get_platform_type(self) -> str:
        return PLATFORM_YOUTUBE

    def close(self) -> None:
        if self.client is not None:
            self.client.disconnect()

    # -- channel info ------------------------------------------------------
    def get_channel_info(self, target: CrawlTarget) -> ChannelData:
        """`youtube_crawler.go:192-243`."""
        self.validate_target(target)
        if not self.initialized:
            raise RuntimeError("crawler not initialized")
        target = dataclasses.replace(target,
                                     id=youtube_channel_id(target.id))
        channel = self.client.get_channel_info(target.id)
        # Identity is the canonical UC… id the API resolved, not the seed's
        # @handle/user-Name form — otherwise the same channel discovered
        # later via its UC id gets a second identity and the built
        # /channel/<id> URL is a non-existent shape for handles.
        canonical_id = channel.id or target.id
        url = _channel_url(canonical_id)
        return ChannelData(
            channel_id=canonical_id,
            channel_name=channel.title,
            channel_description=channel.description,
            channel_url=url,
            channel_url_external=url,
            channel_profile_image=channel.thumbnails.get("default", ""),
            country_code=channel.country,
            published_at=channel.published_at,
            channel_engagement_data=EngagementData(
                follower_count=channel.subscriber_count,
                views_count=channel.view_count,
                post_count=channel.video_count,
            ),
        )

    # -- the crawl ---------------------------------------------------------
    def fetch_messages(self, job: CrawlJob) -> CrawlResult:
        """Sampling switch + parallel conversion (`youtube_crawler.go:245-443`).

        Fetch-level failures raise (the runner's batch path isolates them);
        per-video conversion failures are contained into `result.errors`."""
        try:
            return self._fetch_messages(job)
        except Exception as e:  # panic-recovery parity (`:247-262`)
            logger.error("failure in YouTube fetch_messages", extra={
                "channel_id": job.target.id, "error": str(e),
                "sampling_method": self.sampling_method})
            raise

    def _fetch_messages(self, job: CrawlJob) -> CrawlResult:
        self.validate_target(job.target)
        if not self.initialized:
            raise RuntimeError("crawler not initialized")
        # Seed URLs arrive whole from the layer runner; resolve them to the
        # bare channel identifier the Data API expects (case preserved).
        job = dataclasses.replace(job, target=dataclasses.replace(
            job.target, id=youtube_channel_id(job.target.id)))

        if self.sampling_method == SAMPLING_CHANNEL:
            videos = self.client.get_videos_from_channel(
                job.target.id, job.from_time, job.to_time, job.limit)
        elif self.sampling_method == SAMPLING_RANDOM:
            # Rough cap so all prefix matches get processed (`:303`);
            # samples_remaining unset -> one full batch, not silently zero.
            sample_target = (min(50, job.samples_remaining)
                             if job.samples_remaining > 0 else 50)
            videos = self.client.get_random_videos(
                job.from_time, job.to_time, sample_target)
        elif self.sampling_method == SAMPLING_SNOWBALL:
            seeds = list(self.seed_channels)
            if job.target.id and job.target.id not in seeds:
                seeds.insert(0, job.target.id)
            if not seeds:
                raise ValueError(
                    "no seed channels available for snowball sampling")
            videos = self.client.get_snowball_videos(
                seeds, job.from_time, job.to_time, job.limit)
        else:
            raise ValueError(
                f"unknown sampling method: {self.sampling_method}")

        if self.min_channel_videos > 0:
            videos = [v for v in videos if self._channel_video_count(
                v.channel_id) >= self.min_channel_videos]

        posts: List[Post] = []
        errors: List[str] = []
        lock = threading.Lock()

        def convert_and_store(video: YouTubeVideo) -> None:
            try:
                post = self.convert_video_to_post(video)
            except Exception as e:  # contain per-video failures
                logger.error("failed to convert video", extra={
                    "video_id": video.id, "error": str(e)})
                with lock:
                    errors.append(f"{video.id}: {e}")
                return
            if job.null_validator is not None:
                result = job.null_validator.validate_post(post)
                if not result.valid:
                    logger.error("missing critical fields in youtube post",
                                 extra={"errors": result.errors})
            if self.sm is not None:
                try:
                    self.sm.store_post(video.channel_id, post)
                except Exception as e:
                    logger.error("failed to save video post", extra={
                        "video_id": video.id, "error": str(e)})
            with lock:
                posts.append(post)

        with ThreadPoolExecutor(max_workers=MAX_POST_WORKERS,
                                thread_name_prefix="yt-convert") as pool:
            list(pool.map(convert_and_store, videos))

        if job.sample_size > 0:
            posts = apply_sampling(posts, job.sample_size)
        return CrawlResult(posts=posts, errors=errors)

    def _channel_video_count(self, channel_id: str) -> int:
        try:
            return self.client.get_channel_info(channel_id).video_count
        except Exception as e:
            logger.debug("channel video-count probe failed; treating as 0",
                         extra={"channel_id": channel_id, "error": str(e)})
            return 0

    # -- video -> Post (`youtube_crawler.go:530-838`) ----------------------
    def convert_video_to_post(self, video: YouTubeVideo) -> Post:
        channel: Optional[YouTubeChannel]
        try:
            channel = self.client.get_channel_info(video.channel_id)
            channel_name = channel.title
        except Exception as e:
            logger.warning("failed to get channel info for conversion", extra={
                "channel_id": video.channel_id, "error": str(e)})
            channel = None
            channel_name = video.channel_id

        engagement = int(video.like_count + video.comment_count
                         + video.view_count // 100)
        video_url = f"https://www.youtube.com/watch?v={video.id}"

        video_length: Optional[int] = None
        if video.duration and video.duration != "P0D":  # P0D -> null (`:634`)
            try:
                video_length = parse_iso8601_duration(video.duration)
            except ValueError as e:
                logger.warning("failed to parse video duration", extra={
                    "duration": video.duration, "video_id": video.id,
                    "log_tag": "FOCUS", "error": str(e)})

        ocr_data = [OCRData(thumb_url=url,
                            ocr_text=f"YouTube thumbnail: {quality} quality")
                    for quality, url in video.thumbnails.items() if url]

        channel_url = _channel_url(video.channel_id)
        if channel is not None:
            channel_data = ChannelData(
                channel_id=video.channel_id,
                channel_name=channel.title,
                channel_description=channel.description,
                channel_profile_image=channel.thumbnails.get("default", ""),
                channel_engagement_data=EngagementData(
                    follower_count=channel.subscriber_count,
                    post_count=channel.video_count,
                    views_count=channel.view_count,
                ),
                channel_url_external=channel_url,
                channel_url=channel_url,
                country_code=channel.country,
                published_at=channel.published_at,
            )
        else:
            # Fallback: engagement from the video itself (`:800-826`).
            channel_data = ChannelData(
                channel_id=video.channel_id,
                channel_name=channel_name,
                channel_engagement_data=EngagementData(
                    views_count=video.view_count,
                    like_count=video.like_count,
                    comment_count=video.comment_count,
                ),
                channel_url_external=channel_url,
                channel_url=channel_url,
                published_at=video.published_at,
            )

        now = utcnow()
        return Post(
            post_link=video_url,
            channel_id=video.channel_id,
            post_uid=video.id,
            url=video_url,
            published_at=video.published_at,
            created_at=now,
            language_code=video.language,
            engagement=engagement,
            view_count=video.view_count,
            like_count=video.like_count,
            comment_count=video.comment_count,
            crawl_label=self.crawl_label,
            channel_name=channel_name,
            video_length=video_length,
            platform_name="youtube",
            ocr_data=ocr_data,
            performance_scores=PerformanceScores(
                likes=video.like_count, comments=video.comment_count,
                views=float(video.view_count)),
            has_embed_media=True,
            description=video.description,
            post_type=["video"],
            post_title=video.title,
            media_data=MediaData(document_name=(
                f"{video.id}-{sanitize_filename(video.title)}.mp4")),
            likes_count=video.like_count,
            comments_count=video.comment_count,
            views_count=video.view_count,
            searchable_text=f"{video.title} {video.description}",
            all_text=f"{video.title} {video.description}",
            thumb_url=_best_thumbnail(video.thumbnails),
            media_url=video_url,
            reactions={"like": video.like_count},
            outlinks=extract_urls(video.description),
            capture_time=now,
            handle=video.channel_id,
            channel_data=channel_data,
        )


def register_youtube_crawler(factory: CrawlerFactory) -> None:
    """`crawler/youtube/adapters.go` registration hook."""
    factory.register_crawler(PLATFORM_YOUTUBE, YouTubeCrawler)
